// apex_tpu native host runtime.
//
// TPU-native counterpart of the reference's C++ host layer (csrc/
// flatten_unflatten.cpp — apex_C's flatten/unflatten bindings — and the
// host side of csrc/multi_tensor_apply.cuh's chunking machinery (U)).
// On TPU the *device* side of those components is XLA/Pallas; what remains
// genuinely native is the host runtime around it:
//
//  - at_pack / at_unpack: multithreaded scatter/gather of N host arrays
//    into one contiguous staging buffer (checkpoint IO, flat-buffer init,
//    host→device staging),
//  - at_crc32: checksums for checkpoint integrity,
//  - at_loader_*: a background-thread prefetching loader over fixed-record
//    binary datasets (the IO role torch DataLoader/DALI play for the
//    reference's examples), double-buffered so Python never waits on disk
//    in steady state.
//
// Exposed with a plain C ABI for ctypes (pybind11 is not available in the
// build image). Build: make -C csrc  (g++ -O3 -shared -fPIC -pthread).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// Bumped on any C-ABI change (arguments, semantics). The ctypes loader
// refuses a library reporting a different version (or none), so a stale
// cached .so that survived a failed rebuild degrades to the numpy
// fallback instead of silently misreading arguments.
static const int32_t kAbiVersion = 2;  // 2: at_loader_open header_bytes

int32_t at_abi_version() { return kAbiVersion; }

// ---------------------------------------------------------------------------
// pack / unpack
// ---------------------------------------------------------------------------

// Parallel gather: copy srcs[i] (sizes[i] bytes) to dst at offsets[i].
// Threads split the *bytes*, not the arrays, so one giant embedding table
// doesn't serialise the copy.
void at_pack(const void** srcs, const int64_t* sizes,
             const int64_t* offsets, int64_t n, void* dst,
             int32_t n_threads) {
  if (n_threads <= 0) {
    n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += sizes[i];
  if (total == 0) return;
  const int64_t kMinPerThread = 1 << 20;  // 1 MiB — below this, spawn fewer
  int64_t want = (total + kMinPerThread - 1) / kMinPerThread;
  if (want < n_threads) n_threads = static_cast<int32_t>(want);
  if (n_threads < 1) n_threads = 1;

  // Prefix sums over the concatenated byte stream; each thread owns a
  // contiguous byte range [lo, hi) of it.
  std::vector<int64_t> cum(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) cum[i + 1] = cum[i] + sizes[i];

  auto worker = [&](int64_t lo, int64_t hi) {
    // first array overlapping lo
    int64_t i = static_cast<int64_t>(
        std::upper_bound(cum.begin(), cum.end(), lo) - cum.begin()) - 1;
    int64_t pos = lo;
    while (pos < hi && i < n) {
      int64_t in_arr = pos - cum[i];                 // offset inside array i
      int64_t avail = sizes[i] - in_arr;
      int64_t len = std::min(avail, hi - pos);
      std::memcpy(static_cast<char*>(dst) + offsets[i] + in_arr,
                  static_cast<const char*>(srcs[i]) + in_arr,
                  static_cast<size_t>(len));
      pos += len;
      ++i;
    }
  };

  if (n_threads == 1) {
    worker(0, total);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (total + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(total, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// Parallel scatter: inverse of at_pack.
void at_unpack(const void* src, const int64_t* sizes,
               const int64_t* offsets, int64_t n, void** dsts,
               int32_t n_threads) {
  if (n_threads <= 0) {
    n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += sizes[i];
  if (total == 0) return;
  const int64_t kMinPerThread = 1 << 20;
  int64_t want = (total + kMinPerThread - 1) / kMinPerThread;
  if (want < n_threads) n_threads = static_cast<int32_t>(want);
  if (n_threads < 1) n_threads = 1;

  std::vector<int64_t> cum(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) cum[i + 1] = cum[i] + sizes[i];

  auto worker = [&](int64_t lo, int64_t hi) {
    int64_t i = static_cast<int64_t>(
        std::upper_bound(cum.begin(), cum.end(), lo) - cum.begin()) - 1;
    int64_t pos = lo;
    while (pos < hi && i < n) {
      int64_t in_arr = pos - cum[i];
      int64_t avail = sizes[i] - in_arr;
      int64_t len = std::min(avail, hi - pos);
      std::memcpy(static_cast<char*>(dsts[i]) + in_arr,
                  static_cast<const char*>(src) + offsets[i] + in_arr,
                  static_cast<size_t>(len));
      pos += len;
      ++i;
    }
  };

  if (n_threads == 1) {
    worker(0, total);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (total + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(total, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// ---------------------------------------------------------------------------
// crc32 (IEEE 802.3, table-driven; matches zlib.crc32)
// ---------------------------------------------------------------------------

static uint32_t g_crc_table[256];
static std::once_flag g_crc_once;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    g_crc_table[i] = c;
  }
}

uint32_t at_crc32(const void* data, int64_t nbytes, uint32_t seed) {
  std::call_once(g_crc_once, crc_init);
  uint32_t c = seed ^ 0xFFFFFFFFu;
  const unsigned char* p = static_cast<const unsigned char*>(data);
  for (int64_t i = 0; i < nbytes; ++i)
    c = g_crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// prefetching fixed-record loader
// ---------------------------------------------------------------------------
//
// Dataset = a binary file of `record_bytes`-sized samples. The loader's
// worker thread reads `batch` records per slot (gather by index for
// shuffled order), cycling an epoch permutation, into `n_slots` staging
// buffers. at_loader_next() hands Python a ready slot pointer;
// at_loader_release() returns it to the pool. Sharding: rank r of w takes
// records where (index % world) == rank — the reference's DistributedSampler
// contract, done in native code.

struct Loader {
  FILE* f = nullptr;
  int64_t record_bytes = 0;
  int64_t header_bytes = 0;    // fixed prefix before the first record
  int64_t n_records = 0;       // records this shard owns
  int64_t batch = 0;
  int32_t n_slots = 0;
  int64_t rank = 0, world = 1;
  uint64_t seed = 0;
  bool shuffle = false;
  std::vector<std::vector<char>> slots;
  std::vector<int> state;      // 0 = free, 1 = ready, 2 = in use
  std::vector<int64_t> seq;    // fill order, so delivery is FIFO
  int64_t fill_seq = 0;
  std::vector<int64_t> order;  // shard-local record indices, permuted
  int64_t cursor = 0;          // position in `order`
  int64_t epoch = 0;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<bool> stop{false};
  std::atomic<int64_t> io_errors{0};

  void reshuffle() {
    order.resize(static_cast<size_t>(n_records));
    for (int64_t i = 0; i < n_records; ++i) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      for (int64_t i = n_records - 1; i > 0; --i) {
        int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
        std::swap(order[i], order[j]);
      }
    }
  }

  void fill(int slot) {
    char* dst = slots[slot].data();
    for (int64_t b = 0; b < batch; ++b) {
      if (cursor >= n_records) {
        cursor = 0;
        ++epoch;
        reshuffle();
      }
      int64_t local = order[cursor++];
      int64_t global = local * world + rank;   // strided shard layout
      if (std::fseek(f, header_bytes + global * record_bytes,
                     SEEK_SET) != 0 ||
          std::fread(dst + b * record_bytes, 1,
                     static_cast<size_t>(record_bytes),
                     f) != static_cast<size_t>(record_bytes)) {
        // zero-fill so the slot stays well-defined, but COUNT the failure
        // — Python raises on it rather than training on silent zeros
        std::memset(dst + b * record_bytes, 0,
                    static_cast<size_t>(record_bytes));
        io_errors.fetch_add(1);
        std::clearerr(f);
      }
    }
  }

  void run() {
    while (!stop.load()) {
      int slot = -1;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          if (stop.load()) return true;
          for (int i = 0; i < n_slots; ++i)
            if (state[i] == 0) return true;
          return false;
        });
        if (stop.load()) return;
        for (int i = 0; i < n_slots; ++i)
          if (state[i] == 0) { slot = i; break; }
      }
      fill(slot);
      {
        std::lock_guard<std::mutex> lk(mu);
        state[slot] = 1;
        seq[slot] = fill_seq++;
      }
      cv_ready.notify_one();
    }
  }
};

void* at_loader_open(const char* path, int64_t record_bytes, int64_t batch,
                     int32_t n_slots, int64_t rank, int64_t world,
                     uint64_t seed, int32_t shuffle,
                     int64_t header_bytes) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  int64_t fsize = std::ftell(f) - header_bytes;
  if (fsize < record_bytes) { std::fclose(f); return nullptr; }
  int64_t total = fsize / record_bytes;
  if (world < 1) world = 1;
  if (rank < 0 || rank >= world) { std::fclose(f); return nullptr; }
  int64_t n_local = total / world;  // drop the ragged tail, every rank equal
  if (n_local < 1) { std::fclose(f); return nullptr; }

  Loader* L = new Loader();
  L->f = f;
  L->record_bytes = record_bytes;
  L->header_bytes = header_bytes;
  L->n_records = n_local;
  L->batch = batch;
  L->n_slots = n_slots < 2 ? 2 : n_slots;
  L->rank = rank;
  L->world = world;
  L->seed = seed;
  L->shuffle = shuffle != 0;
  L->slots.resize(static_cast<size_t>(L->n_slots));
  for (auto& s : L->slots)
    s.resize(static_cast<size_t>(batch * record_bytes));
  L->state.assign(static_cast<size_t>(L->n_slots), 0);
  L->seq.assign(static_cast<size_t>(L->n_slots), 0);
  L->reshuffle();
  L->worker = std::thread(&Loader::run, L);
  return L;
}

// Blocks until a batch is ready; returns its slot id and writes the
// buffer pointer. -1 on shutdown.
int32_t at_loader_next(void* handle, void** out_ptr) {
  Loader* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  int slot = -1;
  L->cv_ready.wait(lk, [&] {
    if (L->stop.load()) return true;
    slot = -1;
    for (int i = 0; i < L->n_slots; ++i)
      if (L->state[i] == 1 &&
          (slot < 0 || L->seq[i] < L->seq[slot]))
        slot = i;
    return slot >= 0;
  });
  if (slot < 0) return -1;
  L->state[slot] = 2;
  *out_ptr = L->slots[slot].data();
  return slot;
}

void at_loader_release(void* handle, int32_t slot) {
  Loader* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    if (slot >= 0 && slot < L->n_slots) L->state[slot] = 0;
  }
  L->cv_free.notify_one();
}

int64_t at_loader_num_records(void* handle) {
  return static_cast<Loader*>(handle)->n_records;
}

int64_t at_loader_io_errors(void* handle) {
  return static_cast<Loader*>(handle)->io_errors.load();
}

void at_loader_close(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  if (L->worker.joinable()) L->worker.join();
  std::fclose(L->f);
  delete L;
}

int32_t at_version() { return 1; }

}  // extern "C"
