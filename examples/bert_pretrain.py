"""BASELINE config #2 — BERT-large pretraining shape.

FusedLAMB + fused LayerNorm under amp O2 (fp16 compute + fp32 masters +
dynamic loss scaling; bf16 needs no scaler and is the TPU default —
--fp16 switches to the parity mode). ZeRO sharding via
--zero (DistributedFusedLAMB, the MLPerf BERT recipe (U)).

Run small (CPU simulation):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/bert_pretrain.py --layers 2 --hidden 128 --steps 3
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig, apply_if_finite, update as scaler_update
from apex_tpu.amp import value_and_scaled_grad
from apex_tpu.models import bert
from apex_tpu.optimizers import distributed_fused_lamb, fused_lamb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--fp16", action="store_true")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1/2: DistributedFusedLAMB shards grads + "
                    "optimizer state over dp")
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO-3: dp-shard the encoder kernels between "
                    "steps (FusedLAMB is whole-leaf-norm, so --fsdp "
                    "needs an elementwise optimizer — it switches the "
                    "run to tree-layout FusedAdam)")
    args = ap.parse_args()

    cfg = bert.BertConfig(
        hidden_size=args.hidden, num_layers=args.layers,
        num_heads=args.heads, seq_len=args.seq, fsdp=args.fsdp,
        compute_dtype=jnp.float16 if args.fp16 else jnp.bfloat16)
    mesh = mx.build_mesh(tp=args.tp)
    scaler = (ScalerConfig() if args.fp16 else ScalerConfig(enabled=False))
    # tree layout off the ZeRO path: leafwise XLA-fused update (the flat
    # Pallas sweep runs interpreted — minutes/step — off-TPU)
    if args.fsdp and args.zero:
        raise SystemExit("--fsdp (ZeRO-3) and --zero (ZeRO-1/2) are "
                         "alternative sharding strategies; pick one")
    if args.fsdp:
        from apex_tpu.optimizers import fused_adam
        opt = fused_adam(args.lr, layout="tree")
    else:
        opt = (distributed_fused_lamb(args.lr) if args.zero
               else fused_lamb(args.lr, layout="tree"))

    init_fn, step_fn = bert.make_mlm_train_step(cfg, mesh, opt, scaler)
    state = init_fn(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, cfg.vocab_size, (args.batch, args.seq)))
    mask = jnp.asarray(rng.rand(args.batch, args.seq) < 0.15, jnp.int32)
    tgt = tok  # "reconstruct the original ids at masked positions"

    for i in range(args.steps):
        state, m = step_fn(state, tok, tgt, mask)
        print(f"step {i} mlm_loss {float(m['loss']):.4f} "
              f"scale {float(m['loss_scale']):.0f}")


if __name__ == "__main__":
    main()
