"""BASELINE config #2 — BERT-large pretraining shape.

FusedLAMB + fused LayerNorm under amp O2 (fp16 compute + fp32 masters +
dynamic loss scaling; bf16 needs no scaler and is the TPU default —
--fp16 switches to the parity mode). ZeRO sharding via
--zero (DistributedFusedLAMB, the MLPerf BERT recipe (U)).

Run small (CPU simulation):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/bert_pretrain.py --layers 2 --hidden 128 --steps 3
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig, apply_if_finite, update as scaler_update
from apex_tpu.amp import value_and_scaled_grad
from apex_tpu.models import bert
from apex_tpu.optimizers import distributed_fused_lamb, fused_lamb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--fp16", action="store_true")
    ap.add_argument("--zero", action="store_true")
    args = ap.parse_args()

    cfg = bert.BertConfig(
        hidden_size=args.hidden, num_layers=args.layers,
        num_heads=args.heads, seq_len=args.seq,
        compute_dtype=jnp.float16 if args.fp16 else jnp.bfloat16)
    mesh = mx.build_mesh(tp=args.tp)
    scaler = (ScalerConfig() if args.fp16 else ScalerConfig(enabled=False))
    # tree layout off the ZeRO path: leafwise XLA-fused update (the flat
    # Pallas sweep runs interpreted — minutes/step — off-TPU)
    opt = (distributed_fused_lamb(args.lr) if args.zero
           else fused_lamb(args.lr, layout="tree"))

    params = jax.jit(lambda k: bert.init(cfg, k))(jax.random.PRNGKey(0))
    pspecs = bert.param_specs(cfg)

    state_pspecs = getattr(opt, "state_pspecs", None)
    if state_pspecs is not None:
        # tree layout: optimizer state mirrors the param tree
        opt_specs = state_pspecs(pspecs)
    else:
        # flat layouts: scalars replicated, buffers sharded over the
        # model (+dp for ZeRO) axes
        opt_specs = jax.tree.map(
            lambda x: P() if x.ndim == 0 else P(("dp", "tp") if args.zero
                                                else ("tp",)),
            jax.eval_shape((lambda p: opt.init(p, dp=mesh.shape["dp"]))
                           if args.zero else opt.init,
                           jax.eval_shape(lambda: bert.init(
                               cfg, jax.random.PRNGKey(0)))))

    def local_step(params, opt_state, sc_state, tok, tgt, mask):
        vag = value_and_scaled_grad(
            lambda p: bert.mlm_loss(cfg, p, tok, tgt, mask), scaler)
        loss, grads, finite = vag(params, scaler_state=sc_state)
        if not args.zero:
            grads = jax.lax.pmean(grads, "dp")
        finite = jax.lax.pmin(finite.astype(jnp.int32), ("dp", "tp")) > 0
        new_p, new_o = opt.step(grads, opt_state, params)
        new_p = apply_if_finite(new_p, params, finite)
        new_o = apply_if_finite(new_o, opt_state, finite)
        return new_p, new_o, scaler_update(scaler, sc_state, finite), \
            jax.lax.pmean(loss, "dp")

    sc_specs = jax.tree.map(lambda _: P(), scaler.init())
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(pspecs, opt_specs, sc_specs, P("dp"), P("dp"), P("dp")),
        out_specs=(pspecs, opt_specs, sc_specs, P()),
        check_vma=False), donate_argnums=(0, 1))

    opt_state = jax.jit(jax.shard_map(
        opt.init, mesh=mesh, in_specs=(pspecs,), out_specs=opt_specs,
        check_vma=False))(params)
    sc_state = scaler.init()

    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, cfg.vocab_size, (args.batch, args.seq)))
    mask = jnp.asarray(rng.rand(args.batch, args.seq) < 0.15, jnp.int32)
    tgt = tok  # "reconstruct the original ids at masked positions"

    for i in range(args.steps):
        params, opt_state, sc_state, loss = step(
            params, opt_state, sc_state, tok, tgt, mask)
        print(f"step {i} mlm_loss {float(loss):.4f} "
              f"scale {float(sc_state.loss_scale):.0f}")


if __name__ == "__main__":
    main()
