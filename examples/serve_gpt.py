"""Serving demo: offline batch mode through the continuous-batching
engine.

No reference analogue — apex is training-only — but the ROADMAP north
star serves heavy traffic, and this is the smallest end-to-end slice of
that: a file of requests (one JSON object per line) flows through
``apex_tpu.serving``'s slot engine, each request decoded with its own
sampling params and stop token, outputs token-identical to a solo
``gpt.generate`` call per request (the engine's oracle test pins this).

Request-file line format (all but ``id``/``prompt`` optional; ``stop``
is a list of stop TOKEN sequences, matched host-side on the streamed
tail with the matched tokens trimmed)::

  {"id": "r0", "prompt": [17, 4, 99], "max_tokens": 16,
   "temperature": 0.8, "top_k": 40, "top_p": 0.95, "seed": 7,
   "eos_token_id": 50256, "stop": [[11, 12]]}

HTTP front end (``apex_tpu.serving.api``): ``--api-port N`` serves the
OpenAI surface (``/v1/chat/completions``, ``/v1/completions`` with SSE
streaming, ``/v1/models``, ``/healthz``) after the batch drains, for
``--api-linger`` seconds (0 = until Ctrl-C). Chat prompts are
byte-level, so give the engine prompt room::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --num-requests 0 --api-port 8000 \
    --max-prompt-len 64 --max-seq-len 128
  curl -N localhost:8000/v1/chat/completions -d '{
    "messages": [{"role": "user", "content": "hi"}],
    "max_tokens": 16, "stream": true}'

Run (CPU simulation; omit --requests for a synthetic trace):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/serve_gpt.py --tp 2 --slots 2

Paged KV cache + chunked prefill (``--page-size``/``--max-pages``/
``--prefill-chunk``): a fixed-size page pool with per-slot block
tables replaces the one-contiguous-stripe-per-slot layout (short
requests stop stranding a full horizon; prefix-template hits share
pages copy-on-write), and prompts longer than one chunk admit in
chunk-sized slices interleaved with decode waves — the synthetic
trace gains a long-prompt line so both paths actually run::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --slots 4 --max-prompt-len 32 \
    --page-size 8 --prefill-chunk 16 --num-requests 8

KV oversubscription (``apex_tpu.serving.hostswap``): ``--host-swap``
adds a host-RAM page tier under the device pool — an idle
conversation parks (its pages gather out through compiled swap
programs to pinned host buffers, its slot and HBM pages free up) and
resumes later bit-identically, so far more conversations stay
resident per chip than the pool holds; under ``PagesExhausted``
pressure the scheduler preempts the lowest-priority tenant's pages
(WFQ-aware, replayed through fault-replay on re-admission, streams
still bit-identical). ``--resume-policy swap|recompute|auto`` picks
scatter-back vs replay-from-snapshot (auto prices it from measured
swap cost). The demo parks every conversation mid-stream and resumes
it::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \\
  python examples/serve_gpt.py --slots 4 --page-size 8 \\
    --max-pages 10 --host-swap --num-requests 8

Observability (``apex_tpu.telemetry``): ``--metrics-port N`` serves
``/metrics`` (Prometheus text), ``/healthz`` (live-wired to the
scheduler's health state machine: 200 ok/degraded, 503
draining/failed), and ``/vars`` (JSON incl. span + recompile state)
from a background thread for the life of the process — scrape while it
serves, or add ``--metrics-linger S`` to keep the endpoint up after the
batch drains. ``--span-trace out.json`` writes the per-request span
timeline as Chrome-trace JSON (open in Perfetto next to a
``profiler.trace`` device capture).

Self-tuning (``apex_tpu.serving.tuner``): ``--autotune`` turns the
hand-set serving knobs into measured choices — a scheduler-owned
controller tunes ``decode_chunk`` / ``pipeline_depth`` /
``max_admit_batch`` / ``spec_k`` online from per-chunk
tokens-per-second EWMAs, switching only among pre-warmed compiled
variants (every declared candidate compiles at warmup; the recompile
guard stays flat), with every probe/switch/freeze a flight-recorder
event replayable from a post-mortem bundle. Composes with
``--fault-plan``: the controller hard-freezes to the base operating
point through rebuild/replay brackets::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --num-requests 8 --max-tokens 24 \
    --autotune "decode_chunk=1,2,4;pipeline_depth=1,2"

SLO observatory (``apex_tpu.telemetry.slo``): ``--slo SPEC`` declares
latency objectives — ``SPEC`` is a comma list of
``pQQ:metric:threshold_s[:tenant]`` objectives over ``ttft`` /
``token_latency`` / ``queue_wait`` / ``e2e`` — and the scheduler then
feeds streaming quantile sketches from its existing timings, runs
multi-window burn-rate alerting against the declared error budgets,
and prints sketch-backed p50/p95/p99 plus per-objective budget status
at exit (with ``--metrics-port``, ``/slo`` serves the live snapshot
and ``serving_slo_*`` gauges ride ``/metrics``)::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --num-requests 8 \
    --slo "p99:ttft:0.2,p95:e2e:1.0"

Chaos (``apex_tpu.serving.resilience``): ``--fault-plan SPEC`` injects
deterministic faults at the engine seams for manual recovery drills —
``SPEC`` is ``random:SEED[:N]`` or a comma list of
``point:index:kind[:arg]``, e.g. ``"fetch:2:nan:1,dispatch:5:error"``.
Interrupted requests are replayed/retried; the run prints what fired
and the final health state.

Fleet (``apex_tpu.serving.fleet``): ``--replicas N`` serves the trace
through a health-aware Router over N engine replicas — submits placed
on the best replica, failover + rolling restarts built in. Kill one
mid-burst and watch every stream complete anyway (the router fails the
interrupted requests over with their emitted prefixes; streams stay
bit-identical)::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --replicas 2 --kill-replica 1@4 \
    --num-requests 8

``--kill-replica i@t`` terminally fails replica ``i`` at its ``t``-th
decode dispatch (a deterministic ``FleetFaultPlan.kill`` drill); the
run prints the fleet summary, per-replica health, and any fleet
incident manifest written next to the replica's own post-mortem
bundle (``--bundle-dir``).

Black box (``apex_tpu.telemetry.flightrec``): ``--bundle-dir DIR``
arms the always-on flight recorder and auto-dumps a self-contained
post-mortem bundle there on any fault detection / watchdog trip /
guard alarm / terminal failure; ``SIGUSR1`` (and ``GET
/debug/bundle`` on the metrics port) dump one on demand, and
``/debug/events?n=K`` tails the live event log. Replay an incident
exactly — or render its timeline with no jax installed::

  python -m apex_tpu.telemetry.replay incidents/bundle-0000-* \
      [--report]

Durable serving (``apex_tpu.serving.journal``): ``--journal-dir DIR``
arms the write-ahead request journal — every submit and every emitted
token is durable at the step boundary, ``SIGTERM`` drains and seals
the journal (a ``SIGKILL`` or power loss merely leaves a torn tail
the next open repairs), and rerunning with the SAME dir resumes every
unfinished stream exactly where it stopped, bit-identical to a run
that was never interrupted::

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --num-requests 8 --journal-dir wal &
  sleep 20 && kill -TERM %1; wait          # or kill -9: same recovery
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python examples/serve_gpt.py --num-requests 8 --journal-dir wal
"""

import argparse
import json

import jax
import jax.numpy as jnp

from apex_tpu import checkpoint as ckpt
from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler


def load_requests(path, vocab_size):
    reqs = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            bad = [t for t in d["prompt"] if not 0 <= int(t) < vocab_size]
            if bad:
                raise ValueError(
                    f"request {d.get('id', i)}: prompt tokens {bad} "
                    f"outside vocab [0, {vocab_size})")
            sp = SamplingParams(
                temperature=d.get("temperature", 0.0),
                top_k=d.get("top_k", 0), top_p=d.get("top_p", 1.0),
                seed=d.get("seed"))
            stop = d.get("stop")
            reqs.append(Request(
                str(d.get("id", f"r{i}")), list(d["prompt"]),
                max_tokens=int(d.get("max_tokens", 16)), sampling=sp,
                eos_token_id=d.get("eos_token_id"),
                stop=[[int(t) for t in s] for s in stop]
                if stop else None))
    return reqs


def synthetic_requests(n, prompt_len, max_tokens, vocab_size,
                       prefix=None, long_prompt_len=0, tenants=None,
                       adapters=0):
    """Seeded stand-in trace: half greedy, half sampled; every third
    request carries a stop sequence (trimmed emission when it fires).
    With ``prefix`` (a pooled template's token list), every other
    request's prompt starts with it — the many-users-one-template
    workload prefix reuse exists for. With ``long_prompt_len > 0``,
    every fourth request (offset 1, so it never collides with a
    prefix row) carries a prompt of that length — the long-admission
    traffic chunked prefill (``--prefill-chunk``) interleaves with
    decode waves instead of stalling everyone's TTFT on. ``tenants``
    (a list of tenant ids) and ``adapters`` (registered LoRA adapter
    count) spread the trace round-robin across tenant identities and
    adapter rows — the many-fine-tunes-one-engine workload the
    tenancy subsystem exists for (adapter-carrying rows skip the
    shared prefix: pooled prefixes are base-weight K/V)."""
    reqs = []
    for i in range(n):
        adapter = (i % (adapters + 1)) if adapters else 0
        tenant = tenants[i % len(tenants)] if tenants else "default"
        if long_prompt_len and i % 4 == 1:
            tail = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(2000 + i), (long_prompt_len,), 0,
                vocab_size)]
        else:
            tail = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(1000 + i),
                (1 + (prompt_len + i) % prompt_len,), 0, vocab_size)]
        prompt = (list(prefix) + tail[:2]) \
            if prefix and i % 2 == 0 and not adapter else tail
        sp = (SamplingParams(temperature=0.9, top_k=20, seed=i)
              if i % 2 else SamplingParams())
        stop = [[(17 * i + 3) % vocab_size,
                 (17 * i + 4) % vocab_size]] if i % 3 == 0 else None
        reqs.append(Request(f"r{i}", prompt, max_tokens=max_tokens,
                            sampling=sp, stop=stop, tenant=tenant,
                            adapter=adapter))
    return reqs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-prompt-len", type=int, default=16)
    ap.add_argument("--max-seq-len", type=int, default=48)
    ap.add_argument("--requests", help="JSONL request file (see module "
                    "docstring); synthetic trace if omitted")
    ap.add_argument("--num-requests", type=int, default=6,
                    help="synthetic-trace size when --requests is omitted")
    ap.add_argument("--max-tokens", type=int, default=8,
                    help="synthetic-trace token budget per request")
    ap.add_argument("--decode-chunk", type=int, default=1,
                    help="tokens per compiled decode dispatch "
                    "(gpt.decode_steps): amortises dispatch latency; "
                    "token streams are identical at any setting")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="decode chunks kept in flight by the "
                    "scheduler (Engine.step_async): 1 = serial loop, "
                    "2+ overlaps host event processing with device "
                    "decode; token streams are identical at any depth")
    ap.add_argument("--ckpt", help=".atck from examples/gpt_train.py "
                    "(--preset tiny); random init if omitted")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics /healthz /vars on this port "
                    "(0 = ephemeral, printed at startup)")
    ap.add_argument("--api-port", type=int, default=None,
                    help="serve the OpenAI-compatible front end "
                    "(apex_tpu.serving.api) on this port after the "
                    "batch drains (0 = ephemeral, printed at startup)")
    ap.add_argument("--api-linger", type=float, default=0.0,
                    help="keep the API endpoint up this many seconds "
                    "(0 = until Ctrl-C)")
    ap.add_argument("--metrics-linger", type=float, default=0.0,
                    help="keep the metrics endpoint up this many "
                    "seconds after the batch drains")
    ap.add_argument("--span-trace", metavar="PATH", default=None,
                    help="write the per-request span timeline as "
                    "Chrome-trace JSON (view in Perfetto)")
    ap.add_argument("--bundle-dir", metavar="DIR", default=None,
                    help="arm the flight recorder and auto-dump "
                    "post-mortem bundles here on fault/watchdog/alarm "
                    "(SIGUSR1 or GET /debug/bundle dump on demand; "
                    "python -m apex_tpu.telemetry.replay replays one)")
    ap.add_argument("--journal-dir", metavar="DIR", default=None,
                    help="arm the durable write-ahead request journal "
                    "(apex_tpu.serving.journal): every submit and "
                    "emitted token is made durable at the fetch "
                    "boundary, SIGTERM drains + seals the journal, "
                    "and rerunning with the SAME dir resumes every "
                    "unfinished stream bit-identically (single "
                    "replica only; fleets journal per replica via "
                    "Router.restart(journal_dir=...))")
    ap.add_argument("--fault-plan", metavar="SPEC", default=None,
                    help="inject deterministic faults at the engine "
                    "seams: 'random:SEED[:N]' or a comma list of "
                    "point:index:kind[:arg] (see "
                    "apex_tpu.serving.resilience.parse_fault_plan); "
                    "with --replicas > 1 it applies to replica 0")
    ap.add_argument("--replicas", type=int, default=1,
                    help="serve through a fleet Router over this many "
                    "engine replicas (health-weighted routing, "
                    "deterministic failover, rolling restarts); 1 = "
                    "the plain single-engine scheduler")
    ap.add_argument("--kill-replica", metavar="I@T", default=None,
                    help="fleet chaos drill: terminally fail replica "
                    "I at its T-th decode dispatch "
                    "(FleetFaultPlan.kill) and show every stream "
                    "complete anyway via failover; needs "
                    "--replicas >= 2")
    ap.add_argument("--autotune", metavar="SPEC", nargs="?",
                    const="default", default=None,
                    help="self-tuning runtime (apex_tpu.serving.tuner):"
                    " tune serving knobs online across pre-warmed "
                    "compiled variants. SPEC is a ';'-separated ladder "
                    "list, e.g. 'decode_chunk=4,8,16;"
                    "pipeline_depth=1,2,3;spec_k=0,3' (each ladder "
                    "must contain the knob's configured base value); "
                    "bare --autotune derives default ladders from "
                    "--decode-chunk/--pipeline-depth/--spec-k. Every "
                    "candidate compiles at warmup "
                    "(EngineConfig.decode_chunks/spec_ks), switching "
                    "never recompiles, every decision is a flight-"
                    "recorder event, and the controller hard-freezes "
                    "during --fault-plan rebuilds/replay")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decoding: draft this many tokens "
                    "per wave from a device-side n-gram drafter and "
                    "verify them in one batched target forward "
                    "(gpt.decode_steps_spec); the scheduler's "
                    "acceptance-EWMA payoff gate flips between the "
                    "spec and plain compiled variants, and token "
                    "streams are bit-identical either way (0 = off)")
    ap.add_argument("--kv-cache-dtype", default="auto",
                    choices=("auto", "bf16", "int8", "fp8"),
                    help="KV-cache storage: int8/fp8 store quantized "
                    "K/V with per-head per-position fp32 scales "
                    "(~2x bf16 / ~4x f32 fewer cache bytes per slot)")
    ap.add_argument("--prefix-template", metavar="IDS", action="append",
                    default=None,
                    help="comma-separated token ids of a shared prompt "
                    "prefix to pool (repeatable): prompts starting "
                    "with it admit by pooled-K/V copy + tail-only "
                    "prefill; synthetic traces prepend the first "
                    "template to half the prompts")
    ap.add_argument("--page-size", type=int, default=0,
                    help="paged KV cache: tokens per page (0 = the "
                    "contiguous one-stripe-per-slot layout). A short "
                    "request then pins only the pages its prompt + "
                    "budget need instead of a full max-seq-len "
                    "stripe, and prefix-template hits share the "
                    "template's pages copy-on-write; token streams "
                    "are bit-identical either way")
    ap.add_argument("--max-pages", type=int, default=0,
                    help="pages in the global pool (paged mode; 0 = "
                    "auto-size so every slot fits a worst-case "
                    "request). Set lower to oversubscribe — admission "
                    "then backpressures when the pool runs dry "
                    "instead of stranding idle capacity")
    ap.add_argument("--host-swap", action="store_true",
                    help="host-RAM page tier under the device pool "
                    "(needs --page-size): idle conversations park to "
                    "pinned host buffers through compiled swap "
                    "programs and resume bit-identically, so the "
                    "chip holds far more conversations than its "
                    "pages; page pressure preempts the lowest-"
                    "priority tenant (WFQ-aware) instead of just "
                    "backpressuring. The demo parks every "
                    "conversation mid-stream and resumes it")
    ap.add_argument("--resume-policy", default="auto",
                    choices=("auto", "swap", "recompute"),
                    help="how a parked conversation comes back: "
                    "'swap' scatters the host payload into fresh "
                    "pages, 'recompute' replays from the emitted-"
                    "prefix snapshot, 'auto' (default) prices swap-in "
                    "against replay from measured swap cost")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked prefill: prompts longer than this "
                    "admit in chunk-sized slices interleaved with "
                    "decode waves, so a long admission stops stalling "
                    "other streams' TTFT (must be a prompt bucket "
                    "dividing --max-prompt-len; 0 = monolithic "
                    "admission). The synthetic trace gains a "
                    "long-prompt line (every 4th request) to "
                    "exercise it")
    ap.add_argument("--adapters", type=int, default=0,
                    help="register this many seeded LoRA adapters "
                    "into the engine's static pool "
                    "(EngineConfig.adapter_slots) and spread the "
                    "synthetic trace round-robin across them + the "
                    "base model — many fine-tunes, one compiled "
                    "batch, zero recompiles")
    ap.add_argument("--tenant-weights", metavar="SPEC", default=None,
                    help="tenant fair-share weights, e.g. 'a:3,b:1' — "
                    "the scheduler's weighted-fair queueing converges "
                    "per-tenant served-token shares to this ratio "
                    "under contention; the synthetic trace spreads "
                    "requests round-robin over the named tenants")
    ap.add_argument("--slo", metavar="SPEC", default=None,
                    help="declare latency SLOs (apex_tpu.telemetry."
                    "slo): a comma list of pQQ:metric:threshold_s"
                    "[:tenant] objectives, e.g. 'p99:ttft:0.2,"
                    "p95:e2e:1.0' (metrics: ttft, token_latency, "
                    "queue_wait, e2e). The scheduler feeds streaming "
                    "quantile sketches + burn-rate error-budget "
                    "machines and the run prints sketch percentiles "
                    "and per-objective budget status at exit")
    ap.add_argument("--tenant-rate", metavar="SPEC", default=None,
                    help="per-tenant token budgets (tokens/s), e.g. "
                    "'a:50': a submit over budget is rejected with a "
                    "retry-after (the API maps it to 429) while other "
                    "tenants are untouched")
    args = ap.parse_args()

    def parse_tenant_spec(spec):
        out = {}
        for part in spec.split(","):
            name, _, val = part.partition(":")
            if not name.strip() or not val:
                raise SystemExit(
                    f"bad tenant spec {part!r} (format name:value,...)")
            out[name.strip()] = float(val)
        return out

    tenancy_cfg = None
    tenant_names = None
    if args.tenant_weights or args.tenant_rate:
        from apex_tpu.serving.tenancy import TenancyConfig

        weights = parse_tenant_spec(args.tenant_weights or "") \
            if args.tenant_weights else {}
        rates = parse_tenant_spec(args.tenant_rate or "") \
            if args.tenant_rate else {}
        tenancy_cfg = TenancyConfig(weights=weights, rates=rates)
        tenant_names = sorted(set(weights) | set(rates)) or None
        print(f"tenancy: weights={weights} rates={rates}")

    slo_cfg = None
    if args.slo:
        from apex_tpu.telemetry.slo import SLOConfig, parse_objective

        try:
            slo_cfg = SLOConfig(objectives=tuple(
                parse_objective(part)
                for part in args.slo.split(",") if part.strip()))
        except ValueError as e:
            raise SystemExit(f"--slo: {e}")
        print("slo objectives: "
              + ", ".join(o.key() for o in slo_cfg.objectives))

    cfg = gpt.GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                        num_heads=4, seq_len=128, remat=False,
                        compute_dtype=jnp.float32,
                        kv_cache_dtype=args.kv_cache_dtype)
    # tp-only mesh: decode state is replicated over dp/pp, so the engine
    # takes exactly tp devices (build_mesh would default dp to fill)
    mesh = mx.build_mesh(tp=args.tp, devices=jax.devices()[:args.tp])
    if args.ckpt:
        from apex_tpu.amp import ScalerConfig
        from apex_tpu.models import training
        from apex_tpu.optimizers import fused_adam
        init_fn, _ = training.make_train_step(
            cfg, mesh, fused_adam(1e-4, layout="tree"),
            ScalerConfig(enabled=False))
        params = ckpt.load_checkpoint(
            args.ckpt, init_fn(jax.random.PRNGKey(0))).params
    else:
        params = gpt.init(cfg, jax.random.PRNGKey(0))

    if args.replicas < 1:
        raise SystemExit("--replicas must be >= 1")
    if args.kill_replica and args.replicas < 2:
        raise SystemExit("--kill-replica needs --replicas >= 2 (a "
                         "fleet of one has nowhere to fail over)")
    fault_plan = None
    if args.fault_plan:
        from apex_tpu.serving.resilience import parse_fault_plan

        fault_plan = parse_fault_plan(args.fault_plan)
        print(f"fault plan: {[s.describe() for s in fault_plan.specs]}")
    kill_plan = None
    if args.kill_replica:
        from apex_tpu.serving.resilience import FleetFaultPlan

        victim, at = args.kill_replica.split("@")
        kill_plan = FleetFaultPlan.kill(int(victim), args.replicas,
                                        at=int(at))
        print(f"fleet kill drill: {kill_plan.describe()}")
    templates = [[int(t) for t in spec.split(",")]
                 for spec in (args.prefix_template or ())]
    tuner_cfg = None
    decode_chunks = spec_ks = None
    if args.autotune is not None:
        from apex_tpu.serving.tuner import KNOBS, TunerConfig

        if args.autotune == "default":
            ladders = {
                "decode_chunk": tuple(sorted(
                    {args.decode_chunk, 2 * args.decode_chunk})),
                "pipeline_depth": tuple(sorted(
                    {1, args.pipeline_depth, args.pipeline_depth + 1})),
            }
            if args.spec_k > 0:
                ladders["spec_k"] = (0, args.spec_k)
        else:
            ladders = {}
            for part in args.autotune.split(";"):
                knob, _, vals = part.partition("=")
                knob = knob.strip()
                if knob not in KNOBS or not vals:
                    raise SystemExit(
                        f"--autotune: bad ladder {part!r} (knobs: "
                        f"{', '.join(KNOBS)}; format knob=v1,v2,...)")
                ladders[knob] = tuple(int(v) for v in vals.split(","))
        tuner_cfg = TunerConfig(**ladders)
        # every declared device-variant candidate becomes a compiled,
        # warmed step variant — the tuner only ever switches among
        # warm programs
        decode_chunks = ladders.get("decode_chunk")
        sk = tuple(sorted(k for k in ladders.get("spec_k", ()) if k))
        spec_ks = sk or None
        print(f"autotune: {ladders}")
    if args.host_swap and not args.page_size:
        raise SystemExit("--host-swap needs --page-size (the host "
                         "tier pages a paged pool)")
    ecfg = EngineConfig(
        slots=args.slots, max_prompt_len=args.max_prompt_len,
        max_seq_len=args.max_seq_len, decode_chunk=args.decode_chunk,
        prefix_pool_slots=len(templates), spec_k=args.spec_k,
        page_size=args.page_size, num_pages=args.max_pages,
        prefill_chunk=args.prefill_chunk,
        host_swap=args.host_swap, resume_policy=args.resume_policy,
        decode_chunks=decode_chunks, spec_ks=spec_ks,
        adapter_slots=args.adapters + 1 if args.adapters else 0)

    def replica_plan(i):
        if kill_plan is not None:
            return kill_plan[i]
        return fault_plan if i == 0 else None

    # compile every program (init/step/retire + each (bucket, k)
    # admission variant + prefix pool inserts/extends) before the first
    # request — admission never traces mid-serve, and recompile_guard
    # could be armed right here
    engines = []
    for i in range(args.replicas):
        e = Engine(cfg, params, mesh, ecfg,
                   fault_plan=replica_plan(i))
        e.warmup()
        engines.append(e)
    engine = engines[0]
    long_len = 0
    if args.prefill_chunk and not args.requests:
        # a long-prompt line in the synthetic trace: longer than one
        # chunk (so it actually admits chunked) and capped to the
        # engine's prompt room
        long_len = min(args.max_prompt_len, 2 * args.prefill_chunk)
    reqs = (load_requests(args.requests, cfg.vocab_size) if args.requests
            else synthetic_requests(args.num_requests, 8, args.max_tokens,
                                    cfg.vocab_size,
                                    prefix=templates[0] if templates
                                    else None,
                                    long_prompt_len=long_len,
                                    tenants=tenant_names,
                                    adapters=args.adapters))

    # telemetry: spans whenever a trace is requested; the registry +
    # process-wide recompile sentinel only when there is a /metrics
    # endpoint to export them through (counters nobody can scrape are
    # pure per-token overhead); the flight recorder whenever bundles
    # OR a metrics endpoint exist (the /debug/events tail)
    registry = spans = server = recorder = None
    if args.span_trace or args.metrics_port is not None:
        from apex_tpu.telemetry import SpanRecorder

        spans = SpanRecorder()
    if args.metrics_port is not None:
        from apex_tpu.telemetry import Registry

        registry = Registry()
        engine.recompile_sentinel(registry=registry)
    if args.bundle_dir is not None or args.metrics_port is not None:
        from apex_tpu.telemetry import FlightRecorder

        recorder = FlightRecorder()

    # offline batch mode submits everything up front — size the queue to
    # the trace instead of dying on backpressure at the default 256
    bundle_meta = ({"params": {"ckpt": args.ckpt}} if args.ckpt
                   else {"params": {"init_seed": 0}})
    journaled_ids = set()
    if args.journal_dir is not None and args.replicas > 1:
        raise SystemExit(
            "--journal-dir journals the single-replica path only; "
            "fleets journal per replica and recover through "
            "Router.restart(i, journal_dir=...)")
    if args.replicas > 1:
        from apex_tpu.serving.fleet import Router
        from apex_tpu.serving.resilience import ResilienceConfig

        # per-engine serving metrics would collide name-for-name in
        # one registry, so the fleet registry carries the router's
        # per-replica-labeled serving_fleet_* surface instead; the
        # shared recorder gives ONE merged incident timeline. The
        # kill drill needs retry headroom (see FleetFaultPlan.kill).
        # fleet tenancy split: WFQ weights apply per replica, RATE
        # limits apply at the router's ingress (one fleet-wide bucket
        # per tenant — per-replica buckets would multiply the cap by
        # the replica count)
        rep_tenancy = fleet_tenancy = None
        if tenancy_cfg is not None:
            from apex_tpu.serving.tenancy import TenancyConfig

            if dict(tenancy_cfg.weights):
                rep_tenancy = TenancyConfig(
                    weights=tenancy_cfg.weights)
            if dict(tenancy_cfg.rates):
                fleet_tenancy = TenancyConfig(rates=tenancy_cfg.rates)
        replica_scheds = [
            Scheduler(e, max_queue=max(256, len(reqs)), spans=spans,
                      pipeline_depth=args.pipeline_depth,
                      recorder=recorder, bundle_dir=args.bundle_dir,
                      bundle_meta=bundle_meta, tuner=tuner_cfg,
                      tenancy=rep_tenancy, slo=slo_cfg,
                      resilience=ResilienceConfig(max_retries=8))
            for e in engines]
        sched = Router(replica_scheds, registry=registry,
                       recorder=recorder, bundle_dir=args.bundle_dir,
                       tenancy=fleet_tenancy)
        for t in templates:  # every replica serves the hit
            sched.register_prefix(t)
        for i in range(args.adapters):
            # fleet-wide: same ids mean the same weights on every
            # replica, so failover streams stay bit-identical
            sched.register_adapter(seed=100 + i)
        bundle_sched = replica_scheds[0]   # SIGUSR1 / /debug/bundle
    else:
        journal = None
        if args.journal_dir is not None:
            from apex_tpu.serving.journal import Journal

            # opening repair-scans: a torn tail from a crash is
            # truncated at the last complete record before append
            journal = Journal(args.journal_dir)
            resume_seq = journal.seq
        sched = Scheduler(engine, max_queue=max(256, len(reqs)),
                          registry=registry, spans=spans,
                          pipeline_depth=args.pipeline_depth,
                          recorder=recorder, bundle_dir=args.bundle_dir,
                          tuner=tuner_cfg, tenancy=tenancy_cfg,
                          slo=slo_cfg, journal=journal,
                          # params provenance: telemetry.replay rebuilds
                          # the model from a bundle with this
                          bundle_meta=bundle_meta)
        for t in templates:  # after warmup (which resets the pool)
            engine.register_prefix(t)
        for i in range(args.adapters):
            sched.register_adapter(seed=100 + i)
        if journal is not None and resume_seq:
            # warm restart: resubmit every unfinished journaled stream
            # with its emitted prefix (it continues bit-identically),
            # and keep finished ids out of this run's trace
            from apex_tpu.serving.journal import (replay_into,
                                                  replay_state,
                                                  scan_journal)

            journaled_ids = set(replay_state(
                scan_journal(args.journal_dir)[0]).requests)
            report = replay_into(sched, args.journal_dir)
            print(f"journal: resumed {report.requests} unfinished "
                  f"request(s) from {args.journal_dir} "
                  f"({report.adapters} adapters, {report.prefixes} "
                  f"prefixes replayed)")
        bundle_sched = sched
    if args.bundle_dir is not None:
        import signal

        # SIGUSR-style on-demand dump: kill -USR1 <pid>. A disk error
        # here must not take down the serving loop the handler
        # interrupted (same policy as the scheduler's auto-dump path).
        def _dump_on_signal(*_):
            try:
                print(f"bundle: {bundle_sched.dump_bundle('sigusr1')}")
            except OSError as e:
                print(f"bundle dump failed: {e}")

        if hasattr(signal, "SIGUSR1"):
            signal.signal(signal.SIGUSR1, _dump_on_signal)
        print(f"black box armed: bundles -> {args.bundle_dir} "
              f"(SIGUSR1 dumps on demand)")
    shutdown = {"requested": False}
    if args.journal_dir is not None:
        import signal

        # graceful shutdown: the handler only sets a flag — the serve
        # loop breaks at the next STEP boundary, where the journal's
        # fetch-boundary commit has already made every emitted token
        # durable (same policy as the SIGUSR1 handler: no real work
        # inside a signal frame)
        def _on_sigterm(*_):
            shutdown["requested"] = True

        if hasattr(signal, "SIGTERM"):
            signal.signal(signal.SIGTERM, _on_sigterm)
        print(f"durable journal armed: {args.journal_dir} (SIGTERM "
              f"drains + seals; rerun with the same --journal-dir to "
              f"resume unfinished streams)")
    if args.metrics_port is not None:
        from apex_tpu.telemetry import start_metrics_server

        # /healthz answers from the scheduler's live health machine
        # (200 ok/degraded, 503 draining/failed)
        server = start_metrics_server(
            registry, port=args.metrics_port, spans=spans,
            sentinel=engine.recompile_sentinel(),
            health=sched.health.healthz, recorder=recorder,
            bundle_trigger=(
                (lambda: bundle_sched.dump_bundle("http"))
                if args.bundle_dir is not None else None),
            slo=((sched.slo_status if args.replicas > 1
                  else bundle_sched.slo.status)
                 if slo_cfg is not None else None))
        print(f"metrics: {server.url}/metrics  /healthz  /vars  "
              f"/debug/events"
              + ("  /slo" if slo_cfg is not None else ""))
    from apex_tpu.serving.tenancy import TenantThrottled

    throttled = []
    for r in reqs:
        if r.request_id in journaled_ids:
            continue  # resumed (or already finished) by the journal
        try:
            sched.submit(r)
        except TenantThrottled as e:
            # the offline-demo spelling of the API's 429: report and
            # move on — other tenants' requests are untouched
            throttled.append(r.request_id)
            print(f"request {r.request_id} throttled "
                  f"(tenant {e.tenant!r}, retry in "
                  f"{e.retry_after_s:.1f}s)")
    if args.host_swap and args.replicas == 1:
        # the park-and-resume demo: tick a couple of chunks, park
        # every running conversation (its user walked away — pages
        # swap out to the host tier, the slot frees), show the host
        # tier holding them, then resume; streams stay bit-identical
        for _ in range(2):
            sched.step()
        for rid in sorted(a.request.request_id
                          for a in sched.active.values()):
            sched.pause(rid)
        parked = list(sched.parked_requests)
        if parked:
            print(f"parked {len(parked)} conversation(s) to host RAM "
                  f"({args.resume_policy} resume): {parked}")
            print(f"host tier: " + json.dumps(
                {k: round(v, 1)
                 for k, v in engine.host_tier_stats().items()}))
            for rid in parked:
                sched.resume(rid)
    if args.journal_dir is not None:
        # step loop instead of run_until_idle so SIGTERM can break at
        # a step boundary — everything emitted so far is already
        # durable (the journal commits at every fetch boundary)
        while not sched.idle() and not shutdown["requested"]:
            sched.step()
        if shutdown["requested"]:
            live = (len(sched.active) + len(sched.queue)
                    + len(sched.parked_requests))
            sched.journal.close()
            if args.bundle_dir is not None:
                try:
                    print(f"bundle: {sched.dump_bundle('sigterm')}")
                except OSError as e:
                    print(f"bundle dump failed: {e}")
            print(f"sigterm: drained at a step boundary with "
                  f"{live} stream(s) unfinished — journal sealed; "
                  f"rerun with --journal-dir {args.journal_dir} "
                  f"to resume them bit-identically")
        else:
            sched.journal.close()
    else:
        sched.run_until_idle()
    for r in reqs:
        if r.request_id in throttled:
            continue
        c = sched.completions.get(r.request_id)
        if c is None:
            continue  # interrupted by SIGTERM — journaled, resumable
        print(f"request {c.request_id} [{c.finish_reason}] "
              f"{list(r.prompt)} -> {c.tokens}")
    print("served " + json.dumps(
        {k: round(v, 3) for k, v in sched.summary().items()}))
    if (tenancy_cfg is not None or args.adapters) \
            and args.replicas == 1:
        print("tenants " + json.dumps(sched.tenant_summary()))
    if tuner_cfg is not None and args.replicas == 1:
        s = sched.summary()
        point = {name: int(s[f"tuner_{name}"])
                 for name, _ in tuner_cfg.ladders()
                 if f"tuner_{name}" in s}
        print(f"autotune: state={s['tuner_state']:.0f} "
              f"probes={s['tuner_probes']:.0f} "
              f"switches={s['tuner_switches']:.0f} incumbent={point}")
    if slo_cfg is not None:
        # sketch-backed exit report: percentiles per metric, then each
        # objective's budget verdict (a final evaluation first, so a
        # run shorter than the eval cadence still gets a verdict)
        mon = (sched.slo if args.replicas == 1
               else bundle_sched.slo)
        for m in mon.machines.values():
            m.evaluate(mon.clock())
        for metric in ("ttft", "token_latency", "queue_wait", "e2e"):
            pct = mon.percentiles(metric)
            if not pct.get("count"):
                continue
            print(f"slo {metric}: p50={pct['p50_ms']:.2f}ms "
                  f"p95={pct['p95_ms']:.2f}ms "
                  f"p99={pct['p99_ms']:.2f}ms "
                  f"(n={pct['count']:.0f})")
        for key, m in mon.machines.items():
            st = m.status()
            print(f"slo {key}: state={st['state']} "
                  f"budget_remaining={st['budget_remaining']:.4f} "
                  f"good={st['good']:.0f} bad={st['bad']:.0f}")
        if args.replicas > 1:
            for metric in ("ttft", "e2e"):
                pct = sched.fleet_percentiles(metric)
                if pct.get("count"):
                    print(f"slo fleet {metric}: "
                          f"p99={pct['p99_ms']:.2f}ms "
                          f"(n={pct['count']:.0f}, pooled across "
                          f"{len(sched.replicas)} replicas)")
    if fault_plan is not None:
        print(f"chaos: {len(fault_plan.injected)} fault(s) fired "
              f"({[s.describe() for s in fault_plan.injected]}), "
              f"health={sched.health.state}")
    if kill_plan is not None:
        status, body = sched.health.healthz()
        print(f"fleet after kill drill: {len(kill_plan.injected)} "
              f"fault(s) fired, /healthz {status} {body.strip()!r}")
        for rep in sched.replicas:
            print(f"  replica {rep.index}: state={rep.state} "
                  f"health={rep.health_state} routed={rep.routed} "
                  f"bundles={rep.sched.bundles_written}")
        if sched.incidents_written:
            print(f"  fleet incident manifests: "
                  f"{sched.incidents_written}")
    bundles = getattr(sched, "bundles_written", None)
    if bundles:
        print(f"post-mortem bundles: {bundles} — replay "
              f"with `python -m apex_tpu.telemetry.replay <bundle>`")
    if args.span_trace:
        with open(args.span_trace, "w") as f:
            json.dump(spans.to_chrome_trace(), f)
        print(f"span trace: {args.span_trace} "
              f"({spans.summary()['events']} events)")
    if args.api_port is not None:
        import time

        from apex_tpu.serving.api import start_api_server

        # the ApiServer's driver thread takes over the (now idle)
        # scheduler; the main thread just waits out the linger
        api = start_api_server(sched, port=args.api_port,
                               registry=registry)
        print(f"api: {api.url}/v1/chat/completions  /v1/completions  "
              f"/v1/models  /healthz")
        try:
            if args.api_linger > 0:
                time.sleep(args.api_linger)
            else:
                while True:
                    time.sleep(3600)
        except KeyboardInterrupt:
            pass
        api.stop()
    if server is not None:
        if args.metrics_linger > 0:
            import time

            print(f"metrics endpoint lingering {args.metrics_linger}s "
                  f"at {server.url}")
            time.sleep(args.metrics_linger)
        server.stop()


if __name__ == "__main__":
    main()
