"""BASELINE config #3 — RetinaNet-style detection training.

The apex features this config exercises (BASELINE.md): SyncBatchNorm with
cross-replica Welford statistics over the mesh, FusedSGD, and (from
contrib) the sigmoid focal loss (apex/contrib/focal_loss (U)). The model
is the standard RetinaNet shape — ResNet backbone (`models.resnet
.features`), FPN P3–P5 with lateral + top-down pathways, shared conv
subnets for classification (focal loss) and box regression (smooth-L1) —
written the way an apex user would write theirs: apex ships the
acceleration pieces, the detector lives in the training script.

Targets are synthetic per-anchor tensors: anchor assignment/NMS are data
plumbing orthogonal to the framework capabilities this example pins.

Run (CPU simulation):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/retinanet_detect.py --steps 3 --batch 8 --image 128
"""

import argparse
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu import mesh as mx
from apex_tpu.contrib import sigmoid_focal_loss
from apex_tpu.models import resnet
from apex_tpu.optimizers import fused_sgd

NUM_ANCHORS = 9
FPN_DIM = 256
LEVELS = ("p3", "p4", "p5")


def _conv_init(key, k, cin, cout):
    std = (2.0 / (k * k * cin)) ** 0.5
    return std * jax.random.normal(key, (k, k, cin, cout), jnp.float32)


def init_heads(key, num_classes, backbone_dims):
    ks = iter(jax.random.split(key, 32))
    p = {"lateral": {}, "smooth": {}}
    for lvl, cin in zip(LEVELS, backbone_dims):
        p["lateral"][lvl] = _conv_init(next(ks), 1, cin, FPN_DIM)
        p["smooth"][lvl] = _conv_init(next(ks), 3, FPN_DIM, FPN_DIM)
    # shared 2-conv subnets (RetinaNet uses 4; depth is a dial, not a
    # capability) + prediction convs
    p["cls"] = [
        _conv_init(next(ks), 3, FPN_DIM, FPN_DIM),
        _conv_init(next(ks), 3, FPN_DIM, FPN_DIM),
        _conv_init(next(ks), 3, FPN_DIM, NUM_ANCHORS * num_classes),
    ]
    p["box"] = [
        _conv_init(next(ks), 3, FPN_DIM, FPN_DIM),
        _conv_init(next(ks), 3, FPN_DIM, FPN_DIM),
        _conv_init(next(ks), 3, FPN_DIM, NUM_ANCHORS * 4),
    ]
    return p


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _upsample2(x):
    n, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (n, h, 2, w, 2, c))
    return x.reshape(n, 2 * h, 2 * w, c)


def fpn(p, feats):
    """c3..c5 → p3..p5 (lateral 1x1, top-down nearest-2x, 3x3 smooth)."""
    laterals = {
        lvl: _conv(feats[f"c{i + 3}"], p["lateral"][lvl])
        for i, lvl in enumerate(LEVELS)
    }
    tops = {"p5": laterals["p5"]}
    tops["p4"] = laterals["p4"] + _upsample2(tops["p5"])
    tops["p3"] = laterals["p3"] + _upsample2(tops["p4"])
    return {lvl: _conv(tops[lvl], p["smooth"][lvl]) for lvl in LEVELS}


def _subnet(convs, x):
    for w in convs[:-1]:
        x = jax.nn.relu(_conv(x, w))
    return _conv(x, convs[-1])


def detection_loss(cfg, params, bn_state, heads, images, cls_targets,
                   box_targets, num_classes):
    """Focal + smooth-L1 over all FPN levels; returns (loss, new_bn)."""
    feats, new_bn = resnet.features(cfg, params, bn_state, images,
                                    training=True)
    pyramid = fpn(heads, feats)
    total_cls = jnp.float32(0.0)
    total_box = jnp.float32(0.0)
    n_pos = jnp.float32(0.0)
    for lvl in LEVELS:
        f = pyramid[lvl]
        n, h, w, _ = f.shape
        cls_logits = _subnet(heads["cls"], f).astype(jnp.float32).reshape(
            n, h * w * NUM_ANCHORS, num_classes)
        box_pred = _subnet(heads["box"], f).astype(jnp.float32).reshape(
            n, h * w * NUM_ANCHORS, 4)
        ct = cls_targets[lvl]        # [n, anchors, classes] {0,1}
        bt = box_targets[lvl]        # [n, anchors, 4]
        pos = (ct.sum(-1) > 0).astype(jnp.float32)  # anchors with a box
        total_cls += jnp.sum(sigmoid_focal_loss(cls_logits, ct))
        diff = jnp.abs(box_pred - bt)
        smooth_l1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
        total_box += jnp.sum(smooth_l1.sum(-1) * pos)
        n_pos += jnp.sum(pos)
    denom = jnp.maximum(n_pos, 1.0)
    return (total_cls + total_box) / denom, new_bn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--image", type=int, default=256)
    ap.add_argument("--classes", type=int, default=80)
    ap.add_argument("--depth", type=int, default=50)
    # modest default: the synthetic random box targets make the regression
    # objective pure noise, and noise + momentum at detection-paper LRs
    # diverges within a couple of steps
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()
    if args.image % 32:
        # c5 is stride 32; non-multiples break the exact 2x top-down
        # upsampling and the anchor-count math below
        ap.error(f"--image must be a multiple of 32, got {args.image}")

    mesh = mx.build_mesh(tp=1)
    dp = mesh.devices.size
    # bf16 feeds the MXU on TPU; the CPU backend's bf16 convs fall off the
    # vectorised path (orders of magnitude slower), so simulation runs fp32
    cdt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    cfg = resnet.ResNetConfig(depth=args.depth, bn_axis="dp",  # SyncBN
                              compute_dtype=cdt)
    key = jax.random.PRNGKey(0)
    params, bn_state = resnet.init(cfg, key)
    dims = [256 * (2 ** i) for i in (1, 2, 3)]  # c3..c5 channels
    heads = init_heads(jax.random.fold_in(key, 1), args.classes, dims)
    # tree layout: leafwise XLA-fused update — no flat-packing copies, and
    # the flat Pallas sweep would run interpreted (minutes/step) on the
    # CPU simulation backend
    opt = fused_sgd(args.lr, momentum=0.9, layout="tree")
    all_params = {"backbone": params, "heads": heads}
    opt_state = opt.init(all_params)

    batch = args.batch * dp
    img = jax.random.normal(
        jax.random.fold_in(key, 2), (batch, args.image, args.image, 3),
        jnp.float32)
    anchors = {lvl: (args.image // s) ** 2 * NUM_ANCHORS
               for lvl, s in zip(LEVELS, (8, 16, 32))}
    kc = jax.random.fold_in(key, 3)
    cls_t = {lvl: (jax.random.uniform(jax.random.fold_in(kc, i),
                                      (batch, a, args.classes)) > 0.999
                   ).astype(jnp.float32)
             for i, (lvl, a) in enumerate(anchors.items())}
    box_t = {lvl: jax.random.normal(jax.random.fold_in(kc, 10 + i),
                                    (batch, a, 4))
             for i, (lvl, a) in enumerate(anchors.items())}

    dspec = P("dp")

    def local_step(all_p, opt_st, bn_st, im, ct, bt):
        def lf(ap_):
            return detection_loss(cfg, ap_["backbone"], bn_st,
                                  ap_["heads"], im, ct, bt, args.classes)

        (loss, new_bn), grads = jax.value_and_grad(lf, has_aux=True)(all_p)
        grads = lax.pmean(grads, "dp")
        new_p, new_opt = opt.step(grads, opt_st, all_p)
        return new_p, new_opt, new_bn, lax.pmean(loss, "dp")

    bn_specs = jax.tree.map(lambda _: P(), bn_state)
    pspecs = jax.tree.map(lambda _: P(), all_params)
    ospecs = opt.state_pspecs(pspecs)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(pspecs, ospecs, bn_specs, dspec, dspec, dspec),
        out_specs=(pspecs, ospecs, bn_specs, P()),
        check_vma=False))

    for i in range(args.steps):
        t0 = time.perf_counter()
        all_params, opt_state, bn_state, loss = step(
            all_params, opt_state, bn_state, img, cls_t, box_t)
        loss_v = float(loss)
        print(f"step {i}: loss {loss_v:.4f} "
              f"({time.perf_counter() - t0:.2f}s)", flush=True)


if __name__ == "__main__":
    main()
