"""Decode demo: greedy / sampled continuation from the flagship GPT.

No reference analogue — apex ships no inference path (SURVEY.md §1) —
but a training framework whose checkpoints cannot be decoded is half a
framework. Loads an ``.atck`` checkpoint saved by examples/gpt_train.py
(or random init), then generates with the KV-cache path that is pinned
token-for-token to the teacher-forced forward.

Run (CPU simulation):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/generate.py --tp 2 --n-new 16
"""

import argparse

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import mesh as mx
from apex_tpu.models import gpt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--n-new", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0,
                    help="sample only among the k best logits (0 = off)")
    ap.add_argument("--beams", type=int, default=0,
                    help="beam search width (0 = greedy/sampled "
                    "generate); prints each batch row's best beam "
                    "and its total log-prob")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="nucleus sampling mass (1.0 = off)")
    ap.add_argument("--ckpt", help=".atck from examples/gpt_train.py "
                    "(--preset tiny); random init if omitted")
    args = ap.parse_args()

    cfg = gpt.GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                        num_heads=4, seq_len=128, remat=False,
                        compute_dtype=jnp.float32)
    mesh = mx.build_mesh(tp=args.tp)
    if args.ckpt:
        # gpt_train saves a TrainState; restore just the params leaf
        from apex_tpu.amp import ScalerConfig
        from apex_tpu.models import training
        from apex_tpu.optimizers import fused_adam
        init_fn, _ = training.make_train_step(
            cfg, mesh, fused_adam(1e-4, layout="tree"),
            ScalerConfig(enabled=False))
        params = ckpt.load_checkpoint(
            args.ckpt, init_fn(jax.random.PRNGKey(0))).params
    else:
        params = gpt.init(cfg, jax.random.PRNGKey(0))

    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (args.batch, args.prompt_len), 0,
        cfg.vocab_size)
    key = jax.random.PRNGKey(2)
    if args.beams > 0:
        if args.temperature > 0 or args.top_k or args.top_p != 1.0:
            raise SystemExit(
                "--beams is deterministic max-probability search; "
                "--temperature/--top-k/--top-p apply to generate only")
        seqs, scores = jax.jit(jax.shard_map(
            lambda p, t: gpt.beam_search(
                cfg, p, t, args.n_new, num_beams=args.beams),
            mesh=mesh, in_specs=(gpt.param_specs(cfg), P(None, None)),
            out_specs=(P(None, None, None), P(None, None)),
            check_vma=False))(params, prompt)
        for i in range(args.batch):
            print(f"prompt {list(map(int, prompt[i]))} -> "
                  f"{list(map(int, seqs[i, 0]))} "
                  f"(logp {float(scores[i, 0]):.3f})")
        return
    out = jax.jit(jax.shard_map(
        lambda p, t: gpt.generate(
            cfg, p, t, args.n_new, temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p,
            key=key if args.temperature > 0 else None),
        mesh=mesh, in_specs=(gpt.param_specs(cfg), P(None, None)),
        out_specs=P(None, None), check_vma=False))(params, prompt)
    for i in range(args.batch):
        print(f"prompt {list(map(int, prompt[i]))} -> "
              f"{list(map(int, out[i]))}")


if __name__ == "__main__":
    main()
