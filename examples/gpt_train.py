"""BASELINE configs #4/#5 — GPT training over TP / PP×TP meshes.

Config #4: GPT-2 355M, TP=8 over ICI    → --tp 8 --preset 355m
Config #5: Megatron-GPT 2.7B, PP×TP     → --tp 8 --pp 8 --preset 2p7b
                                          --n-micro 8 --vpp 2

Everything (amp, grad sync, pipeline schedule, fused optimizer) comes from
apex_tpu.models.training.make_train_step — this script is argument
plumbing plus data/metrics wiring: the native prefetching TokenLoader
(--data, synthetic tokens otherwise), per-step StepTimer/MetricsLogger,
and .atck checkpoint save/resume (--ckpt).

Run small (CPU simulation):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/gpt_train.py --preset tiny --tp 2 --pp 2 --n-micro 2

MoE (no apex analogue): --experts 8 --ep 2 shards 8 experts over an
ep=2 mesh axis (Switch/GShard routing, aux loss folded into the loss).
"""

import argparse
import itertools
import os

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import checkpoint as ckpt
from apex_tpu import data as atdata
from apex_tpu import mesh as mx
from apex_tpu import profiler
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam

PRESETS = {
    "tiny": dict(vocab_size=1024, hidden_size=128, num_layers=4,
                 num_heads=4, seq_len=128),
    "355m": dict(vocab_size=50304, hidden_size=1024, num_layers=24,
                 num_heads=16, seq_len=1024),
    "2p7b": dict(vocab_size=50304, hidden_size=2560, num_layers=32,
                 num_heads=32, seq_len=1024),
}


def main():
    # repo-local persistent compile cache (JAX_COMPILATION_CACHE_DIR
    # overrides; empty disables); measured 4x faster warm start on TPU
    from apex_tpu._capabilities import enable_compilation_cache
    enable_compilation_cache()

    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny", choices=sorted(PRESETS))
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--cp", type=int, default=1,
                    help="context parallelism: ring attention over cp "
                    "seq shards (long-context mode)")
    ap.add_argument("--experts", type=int, default=0,
                    help="mixture of experts: replace every MLP with this "
                    "many experts (0 = dense)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert parallelism: shard experts over an "
                    "ep mesh axis (needs --experts divisible by ep; "
                    "requires --opt-layout tree)")
    ap.add_argument("--n-micro", type=int, default=1)
    ap.add_argument("--vpp", type=int, default=1)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--clip-grad-norm", type=float, default=None,
                    help="global-L2 grad clip inside the fused step "
                    "(the reference loop's clip_grad_norm_ between "
                    "unscale and optimizer.step)")
    ap.add_argument("--no-sp", action="store_true")
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO-3: dp-shard the layer kernels between "
                    "steps (per-layer all-gather; needs --opt-layout "
                    "tree and hidden %% dp == 0)")
    ap.add_argument("--data", help="binary token file (apex_tpu.data "
                    "format); synthetic tokens if omitted")
    ap.add_argument("--ckpt", help=".atck checkpoint path to save/resume")
    ap.add_argument("--metrics", help="JSONL metrics path")
    ap.add_argument("--remat-policy", default=None,
                    choices=["dots", "qkv_fc1", "fc1", "qkv_fc1_attn",
                             "fc1_attn"],
                    help="selective-recompute policy (the *_attn variants "
                    "imply --attn-impl flash; bench uses qkv_fc1_attn)")
    ap.add_argument("--attn-impl", default="auto",
                    choices=["auto", "flash", "xla", "xla_chunked"])
    ap.add_argument("--opt-layout", default="tree",
                    choices=["flat", "tree"],
                    help="optimizer state layout; tree (default) avoids "
                    "flat-packing copies and is the measured-fast choice "
                    "for layer-stacked models. Resuming a checkpoint "
                    "requires the layout it was saved with.")
    ap.add_argument("--ln-impl", default="xla", choices=["xla", "pallas"],
                    help="XLA-fused LN (measured faster in-model) or the "
                    "Pallas kernel")
    args = ap.parse_args()

    # chunked CE once the (cp-local) sequence is long enough to make the
    # logits tensor worth not materialising
    seq = PRESETS[args.preset]["seq_len"]
    ce_chunk = 512 if (seq // args.cp) >= 1024 and (seq // args.cp) % 512 == 0 else 0
    attn_impl = args.attn_impl
    if (args.remat_policy or "").endswith("_attn"):
        # the *_attn policies pin the flash kernel's residuals — they
        # require the flash path explicitly
        if attn_impl == "auto":
            attn_impl = "flash"
        elif attn_impl != "flash" or args.cp > 1:
            raise SystemExit(
                f"--remat-policy {args.remat_policy} requires the flash "
                "attention path (and no --cp); drop --attn-impl "
                f"{args.attn_impl} or pick a non-_attn policy")
    cfg = gpt.GPTConfig(
        sequence_parallel=(args.tp > 1 and args.cp == 1 and not args.no_sp
                           and args.experts == 0),
        context_parallel=(args.cp > 1),
        remat=True, compute_dtype=jnp.bfloat16, fsdp=args.fsdp,
        remat_policy=args.remat_policy, ln_impl=args.ln_impl,
        attn_impl=attn_impl, ce_chunk=ce_chunk,
        num_experts=args.experts, **PRESETS[args.preset])
    mesh = mx.build_mesh(tp=args.tp, pp=args.pp, cp=args.cp, ep=args.ep)
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(args.lr, layout=args.opt_layout),
        ScalerConfig(enabled=False),
        n_micro=args.n_micro, n_chunks=args.vpp,
        clip_grad_norm=args.clip_grad_norm)

    state = init_fn(jax.random.PRNGKey(0))
    if args.ckpt and ckpt.checkpoint_exists(args.ckpt):
        try:
            state = ckpt.load_checkpoint(args.ckpt, state)
        except KeyError as e:
            raise SystemExit(
                f"checkpoint {args.ckpt} does not match the current "
                f"optimizer-state structure ({e}); if it was saved with a "
                "different --opt-layout, resume with that layout") from e
        print(f"resumed from {args.ckpt} at step {int(state.step)}")

    loader = None
    if args.data:
        loader = atdata.TokenLoader(
            args.data, cfg.seq_len, args.batch, mesh=mesh, seed=0)
        batches = iter(loader)
    else:
        tok = jax.random.randint(
            jax.random.PRNGKey(1), (args.batch, cfg.seq_len), 0,
            cfg.vocab_size)
        batches = itertools.repeat((tok, jnp.roll(tok, -1, axis=1)))

    timer = profiler.StepTimer(tokens_per_step=args.batch * cfg.seq_len)
    log = profiler.MetricsLogger(jsonl_path=args.metrics)
    for i in range(args.steps):
        tok, tgt = next(batches)
        state, m = step_fn(state, tok, tgt)
        timer.tick(m["loss"])
        log.log(i, m)
        print(f"step {i} loss {float(m['loss']):.4f}")
    s = timer.summary()
    if s:
        print(f"{s['tokens_per_sec']:.0f} tokens/s on mesh "
              f"{dict(mesh.shape)} (median {s['median_step_s']*1e3:.1f} "
              f"ms/step)")
    if args.ckpt:
        written = ckpt.save_checkpoint(args.ckpt, state)
        print(f"saved {written}")
    if loader is not None:
        loader.close()
    log.close()


if __name__ == "__main__":
    main()
