"""The smallest complete distributed training loop — apex's
examples/simple/distributed/distributed_data_parallel.py (U) in TPU form.

The reference demo is ~40 lines: torch.distributed init, a toy linear
model, ``amp.initialize(opt_level="O2")``, ``apex.parallel.
DistributedDataParallel`` wrap, a few steps on random data, print the
loss on rank 0. This is the same demo under one SPMD program:

- process groups / multiproc launcher  →  ``mesh.build_mesh()`` (one
  process, every device a mesh entry on the ``dp`` axis)
- DDP wrapper + bucketed NCCL allreduce →  ``parallel.
  DistributedDataParallel.reduce`` (a ``pmean`` XLA schedules —
  ``gradient_average=True``, the reference's default)
- amp O2 + dynamic loss scaling        →  ``amp.initialize("O2",
  half_dtype=float16)`` + functional ``ScalerState`` in the step
- per-rank random batches              →  batch sharded with
  ``PartitionSpec("dp")``

Run (CPU simulation of an 8-device mesh):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/simple_distributed.py
"""

import argparse

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu import mesh as mx
from apex_tpu.amp import apply_if_finite, update
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel import DistributedDataParallel


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--fp16", action="store_true",
                    help="fp16 + dynamic loss scaling (reference default);"
                         " bf16 without scaling otherwise")
    args = ap.parse_args()

    mesh = mx.build_mesh(tp=1)  # all devices on the dp axis

    # Toy model: two-layer MLP, the reference demo's nn.Linear pair.
    k0, k1 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "w1": jax.random.normal(k0, (args.dim, args.dim)) / args.dim**0.5,
        "w2": jax.random.normal(k1, (args.dim, args.dim)) / args.dim**0.5,
    }

    half = jnp.float16 if args.fp16 else jnp.bfloat16
    ctx, apply_fn = amp.initialize(
        lambda p, x: jnp.tanh(x @ p["w1"]) @ p["w2"],
        opt_level="O2", half_dtype=half)
    scaler_cfg = ctx.scaler
    scaler0 = scaler_cfg.init() if scaler_cfg.enabled else None

    opt = fused_adam(1e-3, layout="tree")
    opt_state = jax.jit(opt.init)(params)
    ddp = DistributedDataParallel()  # reduces grads over the dp axis

    def loss_fn(p, x, y):
        return jnp.mean((apply_fn(p, x) - y) ** 2)

    def local_step(params, opt_state, scaler, x, y):
        grad_fn = amp.value_and_scaled_grad(loss_fn, scaler_cfg)
        loss, grads, finite = grad_fn(params, x, y, scaler_state=scaler)
        grads = ddp.reduce(grads)           # the DDP allreduce (U)
        finite = jax.lax.pmin(  # any-rank overflow skips everywhere
            finite.astype(jnp.int32), ddp.axis).astype(bool)
        new_p, new_opt = opt.step(grads, opt_state, params)
        # overflow → keep old params/opt state, shrink the scale
        new_p = apply_if_finite(new_p, params, finite)
        new_opt = apply_if_finite(new_opt, opt_state, finite)
        if scaler is not None:
            scaler = update(scaler_cfg, scaler, finite)
        return new_p, new_opt, scaler, jax.lax.pmean(loss, ddp.axis)

    rspec = jax.tree.map(lambda _: P(), params)
    ospec = jax.tree.map(lambda _: P(), jax.eval_shape(opt.init, params))
    sspec = None if scaler0 is None else jax.tree.map(lambda _: P(), scaler0)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(rspec, ospec, sspec, P("dp"), P("dp")),
        out_specs=(rspec, ospec, sspec, P()),
        check_vma=False), donate_argnums=(0, 1, 2))

    x = jax.random.normal(jax.random.PRNGKey(2), (args.batch, args.dim))
    y = jax.random.normal(jax.random.PRNGKey(3), (args.batch, args.dim))
    scaler = scaler0
    for i in range(args.steps):
        params, opt_state, scaler, loss = step(params, opt_state, scaler, x, y)
        scale = float(scaler.loss_scale) if scaler is not None else 1.0
        print(f"step {i} loss {float(loss):.6f} scale {scale:g}")
    print(f"done: {mesh.devices.size}-device dp mesh, "
          f"policy {'fp16+dynamic' if args.fp16 else 'bf16'}")


if __name__ == "__main__":
    main()
