"""BASELINE config #1 — ResNet-50 ImageNet-style training.

The TPU-native form of examples/imagenet/main_amp.py (U): amp O1 ≈ bf16
compute policy (no loss scaling needed), apex DDP ≈ batch sharded on the
dp mesh axis with grad pmean, FusedSGD with momentum, SyncBatchNorm
optional (config #3's RetinaNet pairing). Data: ``--data file.bin``
streams packed uint8 records through the native prefetch loader
(``apex_tpu.data.ImageLoader`` — the role the reference leaves to the
torch DataLoader + DistributedSampler), normalized on device; without
it, synthetic tensors. ``--val-data`` adds the validate() prec@1/5 leg;
``--ckpt`` the torch.save/--resume round trip.

Run (CPU simulation):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/imagenet_amp.py --steps 5 --batch 32 --image 64
"""

import argparse
import itertools
import time

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu import checkpoint as ckpt
from apex_tpu import data
from apex_tpu import mesh as mx
from apex_tpu.models import resnet
from apex_tpu.optimizers import fused_sgd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--depth", type=int, default=50)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--syncbn", action="store_true")
    ap.add_argument("--data", default=None,
                    help="packed image file (apex_tpu.data.write_image_file)")
    ap.add_argument("--val-data", default=None,
                    help="packed validation image file; reports prec@1/5 "
                    "after training (main_amp.py's validate() (U))")
    ap.add_argument("--val-batches", type=int, default=0,
                    help="cap on eval batches (0 = one full pass; never "
                    "wraps, so every image counts at most once)")
    ap.add_argument("--ckpt", default=None,
                    help=".atck path to save/resume (main_amp.py's "
                    "--resume/torch.save round trip (U))")
    args = ap.parse_args()

    mesh = mx.build_mesh(tp=1)  # pure data parallelism
    dp = mesh.devices.size
    cfg = resnet.ResNetConfig(
        depth=args.depth, bn_axis="dp" if args.syncbn else None,
        compute_dtype=jnp.bfloat16)
    params, bn_state = resnet.init(cfg, jax.random.PRNGKey(0))
    # tree layout: leafwise XLA-fused update (the flat Pallas sweep runs
    # interpreted — minutes per step — on the CPU simulation backend)
    opt = fused_sgd(args.lr, momentum=0.9, weight_decay=1e-4, layout="tree")
    opt_state = jax.jit(opt.init)(params)

    start_step = 0
    if args.ckpt and ckpt.checkpoint_exists(args.ckpt):
        params, bn_state, opt_state, start_step = ckpt.load_checkpoint(
            args.ckpt,
            (params, bn_state, opt_state, jnp.zeros((), jnp.int32)))
        start_step = int(start_step)
        print(f"resumed from {args.ckpt} at step {start_step}")

    def local_step(params, bn_state, opt_state, images, labels):
        if images.dtype == jnp.uint8:  # native-loader batches: uint8 over
            # the wire, dequant+normalize fused into the first conv read
            images = data.normalize_images(images, jnp.float32)
        (l, ns), g = jax.value_and_grad(
            lambda p: resnet.loss(cfg, p, bn_state, images, labels),
            has_aux=True)(params)
        g = jax.lax.pmean(g, "dp")  # apex DDP allreduce (U)
        if not args.syncbn:
            # local BN: each rank updated running stats from its own batch
            # shard; average them so the replicated-out-spec state stays
            # consistent (torch DDP broadcasts buffers; pmean is the
            # all-shards-contribute version)
            ns = jax.lax.pmean(ns, "dp")
        new_p, opt_state = opt.step(g, opt_state, params)
        return new_p, ns, opt_state, jax.lax.pmean(l, "dp")

    pspec = jax.tree.map(lambda _: P(), params)
    sspec = jax.tree.map(lambda _: P(), bn_state)
    ospec = jax.tree.map(lambda x: P(), jax.eval_shape(opt.init, params))
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(pspec, sspec, ospec, P("dp"), P("dp")),
        out_specs=(pspec, sspec, ospec, P()),
        check_vma=False), donate_argnums=(0, 1, 2))

    if args.data:
        # mesh=: multi-host runs stride records per process and place
        # batches dp-sharded (the DistributedSampler contract)
        loader = data.ImageLoader(
            args.data, (args.image, args.image), args.batch, mesh=mesh,
            shuffle=True)
        batches = iter(loader)
    else:
        img = jax.random.normal(
            jax.random.PRNGKey(1), (args.batch, args.image, args.image, 3))
        lbl = jax.random.randint(
            jax.random.PRNGKey(2), (args.batch,), 0, 1000)
        batches = itertools.repeat((img, lbl))

    # print each step's loss one step late: fetching the in-flight value
    # would sync host and device every iteration and stall the loader's
    # prefetch overlap; the lagged fetch syncs on an already-finished step
    t0 = time.perf_counter()
    prev = None
    for i in range(start_step, start_step + args.steps):
        im, lb = next(batches)
        params, bn_state, opt_state, loss = step(
            params, bn_state, opt_state, im, lb)
        if prev is not None:
            print(f"step {i - 1} loss {float(prev):.4f}")
        prev = loss
    if prev is not None:
        print(f"step {start_step + args.steps - 1} loss "
              f"{float(prev):.4f}")  # sync barrier
    dt = time.perf_counter() - t0
    print(f"{args.steps * args.batch / dt:.1f} images/s over {dp} devices")
    if args.data:
        loader.close()
    if args.ckpt:
        written = ckpt.save_checkpoint(
            args.ckpt,
            (params, bn_state, opt_state,
             jnp.asarray(start_step + args.steps, jnp.int32)))
        print(f"saved {written}")

    if args.val_data:
        # eval pass: frozen BN statistics, top-1/top-5 over the val stream
        def local_eval(params, bn_state, images, labels):
            if images.dtype == jnp.uint8:
                images = data.normalize_images(images, jnp.float32)
            logits, _ = resnet.forward(
                cfg, params, bn_state, images, training=False)
            top5 = jax.lax.top_k(logits, 5)[1]
            hit1 = (top5[:, 0] == labels).sum()
            hit5 = (top5 == labels[:, None]).any(axis=1).sum()
            return (jax.lax.psum(hit1, "dp"), jax.lax.psum(hit5, "dp"))

        evaluate = jax.jit(jax.shard_map(
            local_eval, mesh=mesh,
            in_specs=(pspec, sspec, P("dp"), P("dp")),
            out_specs=(P(), P()), check_vma=False))
        val = data.ImageLoader(args.val_data, (args.image, args.image),
                               args.batch, mesh=mesh, shuffle=False)
        # sequential unshuffled reads: capping at num_records/batch means
        # every image is seen at most once (the loader wraps past that,
        # which would silently resample — the reference's validate()
        # iterates the set exactly once)
        avail = val.num_records // args.batch
        n_batches = avail if args.val_batches <= 0 \
            else min(args.val_batches, avail)
        if n_batches < 1:
            raise SystemExit(
                f"--val-data holds {val.num_records} records — fewer than "
                f"one --batch {args.batch}")
        n = h1 = h5 = 0
        for _ in range(n_batches):
            im, lb = val.next()
            a, b = evaluate(params, bn_state, im, lb)
            h1 += int(a)
            h5 += int(b)
            n += args.batch
        val.close()
        print(f"prec@1 {100.0 * h1 / n:.2f}%  prec@5 {100.0 * h5 / n:.2f}% "
              f"over {n} images")


if __name__ == "__main__":
    main()
