"""In-process steady-decode A/B: decode_chunk=8 vs per-token dispatch.

Per the perf-claims convention: one process, value-fetch sync (engine.step
fetches its [B, n] outputs), warm programs, CPU mesh (no chip attached) —
relative numbers only. Two shapes: the dispatch-dominated probe (tiny
model — the CPU proxy for the chip's multi-ms tunnel latency, which is
what chunking amortizes) and the serve-smoke shape (compute-dominated on
CPU: expected ~flat).
"""
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving.engine import Engine, EngineConfig


def steady_tps(cfg, params, ecfg, chunk, n_tokens):
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    eng = Engine(cfg, params, mesh,
                 dataclasses.replace(ecfg, decode_chunk=chunk))
    for s in range(ecfg.slots):  # fill every slot; huge budgets
        eng.admit(s, [1 + s, 2, 3], max_tokens=ecfg.max_seq_len - 4)
    t_warm, _, _ = eng.step()  # warm the step program
    toks = [t_warm]         # warmup tokens join the parity stream
    n_chunks = max(1, n_tokens // (chunk * ecfg.slots))
    timed = 0
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        t, _, _ = eng.step()  # np.asarray fetch = the sync
        toks.append(t)
        timed += t.size
    dt = time.perf_counter() - t0
    return timed / dt, np.concatenate(toks, axis=1)


def run(name, cfg, ecfg, n_tokens):
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    out = {}
    for chunk in (1, 8):
        best, em = 0.0, None
        for _ in range(5):
            tps, em = steady_tps(cfg, params, ecfg, chunk, n_tokens)
            best = max(best, tps)
        out[chunk] = (best, em)
    # bit-identical steady-state tokens, chunk=8 vs chunk=1
    n = min(out[1][1].shape[1], out[8][1].shape[1])
    np.testing.assert_array_equal(out[1][1][:, :n], out[8][1][:, :n])
    print(f"{name}: chunk=1 {out[1][0]:.0f} tok/s, "
          f"chunk=8 {out[8][0]:.0f} tok/s, "
          f"ratio {out[8][0] / out[1][0]:.2f}x (tokens identical)")


tiny = gpt.GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                     num_heads=2, seq_len=128, remat=False,
                     compute_dtype=jnp.float32)
run("tiny 1L/32h (dispatch-dominated)", tiny,
    EngineConfig(slots=4, max_prompt_len=8, max_seq_len=96), 1920)

probe = gpt.GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, seq_len=128, remat=False,
                      compute_dtype=jnp.float32)
run("probe 2L/64h (dispatch-dominated)", probe,
    EngineConfig(slots=4, max_prompt_len=8, max_seq_len=96), 1920)

smoke = gpt.GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                      num_heads=8, seq_len=256, remat=False,
                      compute_dtype=jnp.float32)
run("smoke 4L/256h (compute-dominated on CPU)", smoke,
    EngineConfig(slots=4, max_prompt_len=16, max_seq_len=64), 480)
