"""In-process A/B: our flash kernels vs stock pallas flash/splash at the
355M bench attention shape (b=16, h=16, s=1024, d=64, causal, bf16).

Each candidate: jit of lax.scan over ITERS chained calls (out feeds next
q), value-fetch sync. Ratios within this process are the signal.
"""

import functools
import time

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu._capabilities import enable_compilation_cache

enable_compilation_cache()

import importlib
fa = importlib.import_module("apex_tpu.kernels.flash_attention")

B, H, S, D = 16, 16, 1024, 64
HID = H * D
ITERS = 30
key = jax.random.PRNGKey(0)
kq, kk, kv = jax.random.split(key, 3)
q_bsh = jax.random.normal(kq, (B, S, HID), jnp.bfloat16)
k_bsh = jax.random.normal(kk, (B, S, HID), jnp.bfloat16)
v_bsh = jax.random.normal(kv, (B, S, HID), jnp.bfloat16)

q4 = q_bsh.reshape(B, S, H, D).transpose(0, 2, 1, 3)
k4 = k_bsh.reshape(B, S, H, D).transpose(0, 2, 1, 3)
v4 = v_bsh.reshape(B, S, H, D).transpose(0, 2, 1, 3)


def timeit(name, fn, *args):
    r = fn(*args)
    _ = float(jnp.asarray(r).ravel()[0])  # compile+warm
    t0 = time.perf_counter()
    r = fn(*args)
    _ = float(jnp.asarray(r).ravel()[0])
    dt = time.perf_counter() - t0
    per = dt / ITERS * 1e3
    print(f"{name:28} {per:8.3f} ms/call")
    return per


def chain(call):
    def body(q, _):
        o = call(q)
        return o.astype(q.dtype), ()
    @jax.jit
    def run(q):
        out, _ = lax.scan(body, q, None, length=ITERS)
        return out.astype(jnp.float32).sum()
    return run


# ---- ours, bsh layout (the bench path) ----
ours_bsh = chain(lambda q: fa.flash_attention_bsh(
    q, k_bsh, v_bsh, num_heads=H, causal=True))
timeit("ours bsh fwd", ours_bsh, q_bsh)

# ---- ours, head-major ----
ours_bhsd = chain(lambda q: fa.flash_attention(q, k4, v4, causal=True))
timeit("ours bhsd fwd", ours_bhsd, q4)

# ---- stock flash_attention ----
from jax.experimental.pallas.ops.tpu import flash_attention as stock

stock_fn = chain(lambda q: stock.flash_attention(
    q, k4, v4, causal=True, sm_scale=1.0 / D ** 0.5))
timeit("stock flash fwd", stock_fn, q4)

# ---- stock splash attention ----
try:
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    mask = sm.CausalMask((S, S))
    mgrid = sm.MultiHeadMask([mask] * H)
    kernel = sk.make_splash_mha(
        mask=mgrid, head_shards=1, q_seq_shards=1)
    kernel = jax.vmap(kernel)   # over batch

    def splash_call(q):
        return kernel(q * (1.0 / D ** 0.5), k4, v4)

    splash_fn = chain(splash_call)
    timeit("stock splash fwd", splash_fn, q4)
except Exception as e:
    print("splash failed:", type(e).__name__, str(e)[:200])
EOF
