"""In-process A/B for PR 7's capacity plays (perf-claims convention:
whole-step, interleaved sides, value-fetch sync — Engine.step()'s
fetch IS the sync barrier).

A: quantized KV cache — int8 storage vs the compute-dtype cache.
   Steady-decode tok/s (admissions excluded: one admit wave, then pure
   chunked decode to the budget) + cache bytes per slot. Run at the
   dispatch-dominated 1L/32h probe AND the 4L/256h smoke shape — on
   CPU the XLA fallback DEQUANTIZES the materialised cache per step
   (extra O(B·h·S·d) multiplies the chip kernel does per split-K chunk
   in VMEM), so the smoke shape is the worst case for the fallback and
   the probe shape isolates dispatch overhead.

B: shared-prefix reuse — per-admission latency (TTFT) of a prefix-hit
   admission (compiled gather + tail-bucket prefill) vs cold prefill
   of the same prompt at its full bucket, k=1 both sides.

Usage: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
       XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       PYTHONPATH=/root/repo python .scratch/kv_prefix_ab.py
"""

import dataclasses
import json
import time

import jax
import jax.numpy as jnp

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Admission, Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler

REPS = 7


def steady_decode_tps(eng, n_chunks=24):
    """One admit wave filling every slot, then n_chunks chunked decode
    dispatches; the value fetch in step() is the sync."""
    eng.rebuild_slots()
    items = [Admission(slot=s, prompt=[1 + s, 2, 3], max_tokens=10_000)
             for s in range(eng.slots)]
    # budget beyond horizon is rejected; give each slot the max room
    items = [dataclasses.replace(
        a, max_tokens=eng.engine_cfg.max_seq_len - 3) for a in items]
    eng.admit_many(items)
    chunk = eng.engine_cfg.decode_chunk
    t0 = time.perf_counter()
    toks = 0
    for _ in range(n_chunks):
        out, _, _ = eng.step()   # fetch = sync
        toks += out.size
    dt = time.perf_counter() - t0
    return toks / dt


def ab_quant(cfg, ecfg, label):
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    eng_b = Engine(cfg, params, mesh, ecfg).warmup()
    eng_q = Engine(dataclasses.replace(cfg, kv_cache_dtype="int8"),
                   params, mesh, ecfg).warmup()
    best = {"base": 0.0, "int8": 0.0}
    for _ in range(REPS):  # interleaved: host drift hits both alike
        best["base"] = max(best["base"], steady_decode_tps(eng_b))
        best["int8"] = max(best["int8"], steady_decode_tps(eng_q))
    out = {
        "shape": label,
        "base_tps": round(best["base"], 1),
        "int8_tps": round(best["int8"], 1),
        "int8_over_base": round(best["int8"] / best["base"], 3),
        "base_bytes_per_slot": eng_b.cache_bytes() // ecfg.slots,
        "int8_bytes_per_slot": eng_q.cache_bytes() // ecfg.slots,
        "bytes_ratio": round(eng_b.cache_bytes() / eng_q.cache_bytes(),
                             3),
    }
    eng_b.close()
    eng_q.close()
    return out


def ab_prefix():
    cfg = gpt.GPTConfig(
        vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
        seq_len=256, remat=False, compute_dtype=jnp.float32)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    ecfg = EngineConfig(slots=4, max_prompt_len=32, max_seq_len=48,
                        decode_chunk=8)
    template = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(900), (16,), 0, cfg.vocab_size)]
    eng_h = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, prefix_pool_slots=1)).warmup()
    eng_h.register_prefix(template)
    eng_c = Engine(cfg, params, mesh, ecfg).warmup()

    def trace():
        reqs = []
        for i in range(12):
            tail = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(910 + i), (1 + i % 8,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"p{i}", template + tail, max_tokens=8,
                                sampling=sp))
        return reqs

    best = {}
    toks = {}
    for _ in range(REPS):
        for name, eng in (("hit", eng_h), ("cold", eng_c)):
            sched = Scheduler(eng, pipeline_depth=2, max_admit_batch=1)
            for r in trace():
                sched.submit(r)
            sched.run_until_idle()
            t = {rid: c.tokens for rid, c in sched.completions.items()}
            toks.setdefault(name, t)
            assert toks[name] == t, f"{name} rerun drift"
            s = sched.summary()
            if name not in best or s["ttft_mean_ms"] < \
                    best[name]["ttft_mean_ms"]:
                best[name] = s
    assert toks["hit"] == toks["cold"], "prefix-hit token drift"
    out = {
        "split": 16, "cold_bucket": 32,
        "hit_ttft_ms": round(best["hit"]["ttft_mean_ms"], 2),
        "cold_ttft_ms": round(best["cold"]["ttft_mean_ms"], 2),
        "ttft_speedup": round(best["cold"]["ttft_mean_ms"]
                              / best["hit"]["ttft_mean_ms"], 3),
        "token_drift": 0,
    }
    eng_h.close()
    eng_c.close()
    return out


def main():
    probe = ab_quant(
        gpt.GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                      num_heads=2, seq_len=128, remat=False,
                      compute_dtype=jnp.float32),
        EngineConfig(slots=4, max_prompt_len=8, max_seq_len=96,
                     decode_chunk=8, prompt_buckets=(8,),
                     admit_batch_sizes=(1, 2, 4)),
        "probe_1l32h")
    smoke = ab_quant(
        gpt.GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                      num_heads=8, seq_len=256, remat=False,
                      compute_dtype=jnp.float32),
        EngineConfig(slots=4, max_prompt_len=8, max_seq_len=64,
                     decode_chunk=8, prompt_buckets=(8,),
                     admit_batch_sizes=(1, 2, 4)),
        "smoke_4l256h")
    print(json.dumps({"quant": [probe, smoke], "prefix": ab_prefix()},
                     indent=1))


if __name__ == "__main__":
    main()
