"""In-process A/B: ce_dtype='f32' vs 'compute' at the 355M bench config."""

import time

import jax
import jax.numpy as jnp

from apex_tpu._capabilities import enable_compilation_cache

enable_compilation_cache()

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam

STEPS = 15


def build(ce_dtype):
    cfg = gpt.GPTConfig(
        vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
        seq_len=1024, remat=True, ce_chunk=512, compute_dtype=jnp.bfloat16,
        attn_impl="flash", ln_impl="xla", remat_policy="qkv_fc1_attn",
        ce_dtype=ce_dtype,
    )
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-4, layout="tree"),
        ScalerConfig(enabled=False))
    return cfg, init_fn, step_fn


def run(ce_dtype):
    cfg, init_fn, step_fn = build(ce_dtype)
    state = init_fn(jax.random.PRNGKey(0))
    tok = jax.random.randint(
        jax.random.PRNGKey(1), (16, cfg.seq_len), 0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)
    state, m = step_fn(state, tok, tgt)
    loss0 = float(m["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            state, m = step_fn(state, tok, tgt)
        _ = float(m["loss"])
        best = min(best, time.perf_counter() - t0)
    tps = 16 * cfg.seq_len * STEPS / best
    print(f"ce_dtype={ce_dtype:8} first-step loss {loss0:.6f}  "
          f"{best / STEPS * 1e3:7.1f} ms/step  {tps / 1e3:6.1f}k tok/s")
    return tps


a = run("f32")
b = run("compute")
print(f"compute/f32 speedup: {b / a:.4f}")
