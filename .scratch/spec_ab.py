"""Speculative-decoding A/B — reproduces the `bench.py --mode serve`
``spec_ab`` numbers standalone (docs/DESIGN.md "Serving round 7").

Interleaved best-of-N over ONE pair of warm engines (spec_k=3 vs
spec_k=0 — otherwise identical geometry), value-fetch sync (the
scheduler only counts fetched tokens), two traces:

- ``high``: greedy requests — random-init greedy decode collapses into
  short attractor cycles, which the device-side n-gram drafter replays
  at ~90%+ acceptance. The speculation win case.
- ``adv``: temperature-1.5 sampled requests — near-uniform tokens,
  drafts almost never land; the payoff gate must close after its probe
  chunks and the run must hold the plain engine's numbers (the
  0.74-1.23 host noise band).

Both traces assert BIT-IDENTICAL streams spec-vs-plain: verification
is token-matching against the target's own draws at the plain key fold
points, so speculation is a pure perf knob.

Run:
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=/root/repo python .scratch/spec_ab.py
"""

import dataclasses
import json

import jax
import jax.numpy as jnp

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler

REPS = 5
SPEC_K = 3

cfg = gpt.GPTConfig(  # the serve bench's compute-bound CPU smoke shape
    vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
    seq_len=256, remat=False, compute_dtype=jnp.float32)
ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=96,
                    decode_chunk=4)
mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
params = gpt.init(cfg, jax.random.PRNGKey(0))


def trace(adversarial):
    reqs = []
    for i in range(6):
        p_len = 1 + (11 * i + 5) % ecfg.max_prompt_len
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(700 + i), (p_len,), 0, cfg.vocab_size)]
        sp = (SamplingParams(temperature=1.5, seed=i) if adversarial
              else SamplingParams())
        reqs.append(Request(f"s{i}", prompt, max_tokens=64, sampling=sp))
    return reqs


def run(eng, reqs):
    sched = Scheduler(eng, pipeline_depth=2)
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    return ({rid: c.tokens for rid, c in sched.completions.items()},
            sched.summary())


eng_sp = Engine(cfg, params, mesh,
                dataclasses.replace(ecfg, spec_k=SPEC_K)).warmup()
eng_pl = Engine(cfg, params, mesh, ecfg).warmup()

best, toks = {}, {}
for _ in range(REPS):
    for tr, adv in (("high", False), ("adv", True)):
        for side, eng in (("spec", eng_sp), ("plain", eng_pl)):
            key = f"{tr}_{side}"
            t, s = run(eng, trace(adv))
            toks.setdefault(key, t)
            assert toks[key] == t, f"{key} rerun drift"
            if key not in best or s.get("decode_tokens_per_sec", 0.0) \
                    > best[key].get("decode_tokens_per_sec", 0.0):
                best[key] = s

assert toks["high_spec"] == toks["high_plain"], "high-trace drift"
assert toks["adv_spec"] == toks["adv_plain"], "adversarial drift"
dec = lambda k: best[k].get("decode_tokens_per_sec", 0.0)
print(json.dumps({
    "high_spec": round(dec("high_spec"), 1),
    "high_plain": round(dec("high_plain"), 1),
    "high_speedup": round(dec("high_spec") / dec("high_plain"), 3),
    "high_accept_rate": round(
        best["high_spec"]["spec_accept_rate"], 3),
    "adv_ratio": round(dec("adv_spec") / dec("adv_plain"), 3),
    "adv_gate_state": best["adv_spec"]["spec_gate_state"],
    "token_drift": 0,
}))
eng_sp.close()
eng_pl.close()
