"""Routing-overhead A/B: bare Scheduler vs a 1-replica Router on the
same trace — what does the fleet layer cost when nothing ever fails?

Method (docs/DESIGN.md conventions, PR-11 methodology): one process,
two independent warmed engine+scheduler stacks of the same config
(side A driven directly, side B through a Router), the SAME seeded
burst trace per round with fresh request ids, paired per-round wall
ratios with ALTERNATING side order, median reported. Sync is the
run-to-idle value fetch, never block_until_ready. Token streams
asserted identical across sides every round.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     PYTHONPATH=. python .scratch/fleet_ab.py
"""

import json
import time

import jax

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.fleet import Router
from apex_tpu.serving.scheduler import Scheduler

ROUNDS = 11
N_REQS = 24

cfg = gpt.GPTConfig(
    vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
    seq_len=256, remat=False, compute_dtype=jax.numpy.float32)
ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=48,
                    decode_chunk=4)
mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
params = gpt.init(cfg, jax.random.PRNGKey(0))


def trace(rnd, tag):
    reqs = []
    for i in range(N_REQS):
        p_len = 1 + (5 * i + 3) % ecfg.max_prompt_len
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(100 + i), (p_len,), 0, cfg.vocab_size)]
        sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
              if i % 2 else SamplingParams())
        reqs.append(Request(f"{tag}{rnd}_{i}", prompt, max_tokens=16,
                            sampling=sp))
    return reqs


sched_a = Scheduler(Engine(cfg, params, mesh, ecfg).warmup(),
                    pipeline_depth=2)
router = Router([Scheduler(Engine(cfg, params, mesh, ecfg).warmup(),
                           pipeline_depth=2)])


def run(side, rnd):
    drv = sched_a if side == "bare" else router
    reqs = trace(rnd, side[0])
    t0 = time.perf_counter()
    for r in reqs:
        drv.submit(r)
    drv.run_until_idle()
    wall = time.perf_counter() - t0
    toks = {r.request_id[1:]: drv.completions[r.request_id].tokens
            for r in reqs}
    return wall, toks


# warm both sides (round 0 discarded)
run("bare", 0), run("router", 0)
ratios = []
for rnd in range(1, ROUNDS + 1):
    sides = ("bare", "router") if rnd % 2 else ("router", "bare")
    walls = {}
    streams = {}
    for side in sides:
        walls[side], streams[side] = run(side, rnd)
    assert streams["bare"] == streams["router"], "token drift"
    ratios.append(walls["router"] / walls["bare"])

ratios.sort()
print(json.dumps({
    "metric": "fleet_router_overhead_ratio_router_over_bare",
    "median": round(ratios[len(ratios) // 2], 3),
    "min": round(ratios[0], 3),
    "max": round(ratios[-1], 3),
    "rounds": ROUNDS,
    "requests_per_round": N_REQS,
}))
