"""Cost of the flight recorder (PR 10): in-process interleaved A/B of
the NEW scheduler — recorder OFF (always-on request-record bookkeeping
only) and recorder ON (full event log) — vs the PRE-PR scheduler
loaded verbatim from git HEAD, over ONE shared warm engine per shape,
same burst trace, best-of-N with sides interleaved so host drift hits
all alike. Token parity asserted between every pair of sides.

Run (CPU mesh):
  git show <pre-PR-rev>:apex_tpu/serving/scheduler.py > /tmp/pre_scheduler_pr10.py
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=/root/repo python .scratch/flightrec_ab.py

Also microbenches the hot-path unit costs directly: one
FlightRecorder.record() append (the per-decision price) and one
_record_request + completion-graduation pair (the per-request price) —
the direct bound on added host work, independent of the noisy
end-to-end ratio.
"""

import importlib.util
import json
import sys
import time

import jax
import jax.numpy as jnp

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler as NewScheduler
from apex_tpu.telemetry.flightrec import FlightRecorder

spec = importlib.util.spec_from_file_location(
    "pre_scheduler_pr10", "/tmp/pre_scheduler_pr10.py")
pre_mod = importlib.util.module_from_spec(spec)
# dataclasses resolves cls.__module__ through sys.modules at class
# creation — register before exec
sys.modules["pre_scheduler_pr10"] = pre_mod
spec.loader.exec_module(pre_mod)
PreScheduler = pre_mod.Scheduler

mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])

SHAPES = {
    # the dispatch-dominated probe (worst case for per-chunk host
    # overhead: chunks are fast, so fixed host work per chunk is the
    # largest relative slice)
    "probe_1l32h": (
        gpt.GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                      num_heads=2, seq_len=128, remat=False,
                      compute_dtype=jnp.float32),
        EngineConfig(slots=4, max_prompt_len=32, max_seq_len=96,
                     decode_chunk=8), 24, 16),
    # the compute-bound smoke shape
    "smoke_4l256h": (
        gpt.GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                      num_heads=8, seq_len=256, remat=False,
                      compute_dtype=jnp.float32),
        EngineConfig(slots=4, max_prompt_len=16, max_seq_len=48,
                     decode_chunk=8), 12, 24),
}


def trace(cfg, ecfg, n, mt):
    reqs = []
    for i in range(n):
        p_len = 1 + (11 * i + 5) % ecfg.max_prompt_len
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(900 + i), (p_len,), 0, cfg.vocab_size)]
        sp = (SamplingParams(temperature=0.9, top_k=20, seed=i)
              if i % 2 else SamplingParams())
        reqs.append(Request(f"r{i}", prompt, max_tokens=mt, sampling=sp))
    return reqs


SIDES = (
    ("pre", lambda eng: PreScheduler(eng, pipeline_depth=2)),
    ("off", lambda eng: NewScheduler(eng, pipeline_depth=2)),
    ("on", lambda eng: NewScheduler(eng, pipeline_depth=2,
                                    recorder=FlightRecorder())),
)

out = {}
for name, (cfg, ecfg, n_reqs, mt) in SHAPES.items():
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    engine = Engine(cfg, params, mesh, ecfg).warmup()
    best = {s: 0.0 for s, _ in SIDES}
    ratios = {"off": [], "on": []}
    toks = {}
    events = 0
    for _ in range(7):
        per_round = {}
        for side, make in SIDES:
            sched = make(engine)
            for r in trace(cfg, ecfg, n_reqs, mt):
                sched.submit(r)
            sched.run_until_idle()
            t = {rid: c.tokens for rid, c in sched.completions.items()}
            toks.setdefault(side, t)
            assert toks[side] == t, f"{name}/{side} rerun drift"
            s = sched.summary()
            per_round[side] = s["tokens_per_sec"]
            best[side] = max(best[side], s["tokens_per_sec"])
            if side == "on":
                events = sched.recorder.summary()["events_total"]
        for side in ("off", "on"):
            ratios[side].append(per_round[side] / per_round["pre"])
    assert toks["pre"] == toks["off"] == toks["on"], \
        f"{name} token drift across sides"
    ratios = {s: sorted(r) for s, r in ratios.items()}
    out[name] = {
        "pre_tokens_per_sec": round(best["pre"], 1),
        "off_tokens_per_sec": round(best["off"], 1),
        "on_tokens_per_sec": round(best["on"], 1),
        "off_over_pre_best": round(best["off"] / best["pre"], 4),
        "on_over_pre_best": round(best["on"] / best["pre"], 4),
        "off_over_pre_median": round(ratios["off"][3], 4),
        "on_over_pre_median": round(ratios["on"][3], 4),
        "events_per_run": events,
    }

# direct unit costs of the added hot-path work
rec = FlightRecorder()
N = 200_000
t0 = time.perf_counter()
for i in range(N):
    rec.record("dispatch", False, 8, 1, 4)
record_ns = (time.perf_counter() - t0) / N * 1e9

sched = NewScheduler(engine)
req = trace(cfg, ecfg, 1, 4)[0]
M = 20_000
t0 = time.perf_counter()
for i in range(M):
    sched._record_request(req, 0.0)
    sched._req_records.pop(req.request_id)
req_record_us = (time.perf_counter() - t0) / M * 1e6

out["unit_costs"] = {
    "record_ns_per_event": round(record_ns, 1),
    "request_record_us_per_request": round(req_record_us, 2),
}
print(json.dumps(out, indent=1))
