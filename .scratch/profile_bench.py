"""Capture an xprof trace of the tuned 355M bench step and print the
op_profile category table + top self-time ops."""

import json

import jax
import jax.numpy as jnp

from apex_tpu._capabilities import enable_compilation_cache

enable_compilation_cache()

from apex_tpu import mesh as mx
from apex_tpu import profiler
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam

cfg = gpt.GPTConfig(
    vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
    seq_len=1024, remat=True, ce_chunk=512, compute_dtype=jnp.bfloat16,
    attn_impl="flash", ln_impl="xla", remat_policy="qkv_fc1_attn",
)
batch = 16

mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
init_fn, step_fn = training.make_train_step(
    cfg, mesh, fused_adam(1e-4, layout="tree"), ScalerConfig(enabled=False))
state = init_fn(jax.random.PRNGKey(0))
tok = jax.random.randint(
    jax.random.PRNGKey(1), (batch, cfg.seq_len), 0, cfg.vocab_size)
tgt = jnp.roll(tok, -1, axis=1)

state, m = step_fn(state, tok, tgt)
_ = float(m["loss"])  # warm

logdir = "/root/repo/.scratch/trace"
opts = __import__("jax").profiler.ProfileOptions()
opts.host_tracer_level = 0
opts.python_tracer_level = 0
import jax.profiler as _jp
_jp.start_trace(logdir, profiler_options=opts)
if True:
    for _ in range(3):
        state, m = step_fn(state, tok, tgt)
    _ = float(m["loss"])

_jp.stop_trace()
prof = profiler.op_profile(logdir, top=30)
print("TOTAL", round(prof["total_s"], 4))
cats = sorted(prof["by_category"].items(), key=lambda kv: -kv[1])
for c, s in cats:
    print(f"{s:9.4f}  {c}")
print("---- top ops ----")
for o in prof["top_ops"]:
    print(f"{o['seconds']:8.4f} x{o['count']:<4} {o['category'][:22]:22} "
          f"{o['name'][:60]:60} {o.get('source','')}")
