"""Happy-path cost of the resilience layer (PR 5): in-process
interleaved A/B of the NEW scheduler (fault detection, watchdog, EWMA,
health polling — faults disabled) vs the PRE-PR scheduler loaded
verbatim from git HEAD, over ONE shared warm engine per shape, same
burst trace, best-of-N with sides interleaved so host drift hits both
alike. Token parity asserted between sides.

Run (CPU mesh):
  git show <pre-PR-rev>:apex_tpu/serving/scheduler.py > /tmp/pre_scheduler_pr5.py
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=/root/repo python .scratch/resilience_ab.py

The engine-side seam cost (a `fault_plan is None` check per
admit/dispatch and a no-op plan field on StepHandle) rides BOTH sides
here — it is two attribute checks per dispatch, far below measurement
noise; this A/B isolates the scheduler-side detection machinery, which
is where all the per-chunk work lives.
"""

import importlib.util
import json

import jax
import jax.numpy as jnp

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler as NewScheduler

spec = importlib.util.spec_from_file_location(
    "pre_scheduler_pr5", "/tmp/pre_scheduler_pr5.py")
pre_mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(pre_mod)
PreScheduler = pre_mod.Scheduler

mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])

SHAPES = {
    # the dispatch-dominated probe (worst case for per-chunk host
    # overhead: chunks are fast, so fixed host work per chunk is the
    # largest relative slice)
    "probe_1l32h": (
        gpt.GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                      num_heads=2, seq_len=128, remat=False,
                      compute_dtype=jnp.float32),
        EngineConfig(slots=4, max_prompt_len=32, max_seq_len=96,
                     decode_chunk=8), 24, 16),
    # the compute-bound smoke shape
    "smoke_4l256h": (
        gpt.GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                      num_heads=8, seq_len=256, remat=False,
                      compute_dtype=jnp.float32),
        EngineConfig(slots=4, max_prompt_len=16, max_seq_len=48,
                     decode_chunk=8), 12, 24),
}


def trace(cfg, ecfg, n, mt):
    reqs = []
    for i in range(n):
        p_len = 1 + (11 * i + 5) % ecfg.max_prompt_len
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(900 + i), (p_len,), 0, cfg.vocab_size)]
        sp = (SamplingParams(temperature=0.9, top_k=20, seed=i)
              if i % 2 else SamplingParams())
        reqs.append(Request(f"r{i}", prompt, max_tokens=mt, sampling=sp))
    return reqs


out = {}
for name, (cfg, ecfg, n_reqs, mt) in SHAPES.items():
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    engine = Engine(cfg, params, mesh, ecfg).warmup()
    best = {"pre": 0.0, "new": 0.0}
    toks = {}
    for _ in range(7):
        for side, cls in (("pre", PreScheduler), ("new", NewScheduler)):
            sched = cls(engine, pipeline_depth=2)
            for r in trace(cfg, ecfg, n_reqs, mt):
                sched.submit(r)
            sched.run_until_idle()
            t = {rid: c.tokens for rid, c in sched.completions.items()}
            toks.setdefault(side, t)
            assert toks[side] == t, f"{name}/{side} rerun drift"
            s = sched.summary()
            best[side] = max(best[side], s["tokens_per_sec"])
    assert toks["pre"] == toks["new"], f"{name} pre/new token drift"
    out[name] = {
        "pre_tokens_per_sec": round(best["pre"], 1),
        "new_tokens_per_sec": round(best["new"], 1),
        "new_over_pre": round(best["new"] / best["pre"], 4),
    }
print(json.dumps(out, indent=1))
