"""In-process serving-loop A/B: pipelined + batched/bucketed admission
vs the pre-PR serial loop, under a bursty arrival workload.

Per the perf-claims convention: one process, value-fetch sync (the
scheduler's collect fetches each chunk's [B, n] outputs; admit_many
fetches its first tokens), warm programs (Engine.warmup both engines
first), CPU mesh (no chip attached) — relative numbers only. The two
sides interleave their repetitions so host noise hits both alike.

Baseline ("old") is the pre-pipeline path verbatim: ONE flat prefill
bucket at max_prompt_len, k=1 admits, pipeline_depth=1 (dispatch, then
fetch, strictly serial). "New" is the default engine (bucket +
admission ladders) under the depth-2 pipelined scheduler loop. Token
streams are asserted bit-identical between the two.

Two dispatch-dominated probe shapes (the CPU proxy for the chip's
multi-ms tunnel latency, which is what the pipeline overlaps and
batched admission amortizes) + the serve-smoke shape (compute-dominated
on CPU: pipelining cannot overlap there because buffer DONATION makes
XLA:CPU execute synchronously inside the dispatch call — expected
modest, admission-side-only wins; see docs/DESIGN.md).
"""
import time

import jax
import jax.numpy as jnp

from apex_tpu import mesh as mx
from apex_tpu.models import gpt
from apex_tpu.serving import Request, SamplingParams
from apex_tpu.serving.engine import Engine, EngineConfig
from apex_tpu.serving.scheduler import Scheduler


def burst_trace(n, mpl, max_tokens, vocab):
    """Every request arrives at t=0 — the admission-pressure regime."""
    reqs = []
    for i in range(n):
        p_len = 1 + (11 * i + 5) % mpl
        prompt = [int(t) for t in jax.random.randint(
            jax.random.PRNGKey(300 + i), (p_len,), 0, vocab)]
        sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
              if i % 2 else SamplingParams())
        reqs.append(Request(f"r{i}", prompt, max_tokens=max_tokens,
                            sampling=sp))
    return reqs


def serve_once(eng, reqs, **sched_kw):
    sched = Scheduler(eng, **sched_kw)
    for r in reqs:
        sched.submit(r)
    t0 = time.perf_counter()
    sched.run_until_idle()
    dt = time.perf_counter() - t0
    s = sched.summary()
    return (s["tokens_emitted"] / dt, s,
            {rid: c.tokens for rid, c in sched.completions.items()})


def run(name, cfg, ecfg, n_requests, max_tokens):
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    new_eng = Engine(cfg, params, mesh, ecfg).warmup()
    import dataclasses

    old_eng = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, prompt_buckets=(ecfg.max_prompt_len,),
        admit_batch_sizes=(1,))).warmup()
    mk = lambda: burst_trace(n_requests, ecfg.max_prompt_len,
                             max_tokens, cfg.vocab_size)
    best = {"old": 0.0, "new": 0.0}
    ttft = {"old": 1e9, "new": 1e9}
    toks = {}
    for _ in range(5):
        tps, s, t = serve_once(old_eng, mk(), pipeline_depth=1,
                               max_admit_batch=1)
        toks.setdefault("old", t)
        assert toks["old"] == t, "old rerun drift"
        best["old"] = max(best["old"], tps)
        ttft["old"] = min(ttft["old"], s["ttft_mean_ms"])
        tps, s, t = serve_once(new_eng, mk(), pipeline_depth=2)
        toks.setdefault("new", t)
        assert toks["new"] == t, "new rerun drift"
        best["new"] = max(best["new"], tps)
        ttft["new"] = min(ttft["new"], s["ttft_mean_ms"])
    # the whole point: streams bit-identical, loop/admission-invariant
    assert toks["old"] == toks["new"], "old-vs-new token drift"
    print(f"{name}: old {best['old']:.0f} tok/s, new {best['new']:.0f} "
          f"tok/s, ratio {best['new'] / best['old']:.2f}x | ttft "
          f"{ttft['old']:.1f} -> {ttft['new']:.1f} ms (tokens identical)")


tiny = gpt.GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                     num_heads=2, seq_len=128, remat=False,
                     compute_dtype=jnp.float32)
run("tiny 1L/32h (dispatch-dominated)", tiny,
    EngineConfig(slots=4, max_prompt_len=32, max_seq_len=96,
                 decode_chunk=8), 24, 16)

probe = gpt.GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, seq_len=128, remat=False,
                      compute_dtype=jnp.float32)
run("probe 2L/64h (dispatch-dominated)", probe,
    EngineConfig(slots=4, max_prompt_len=32, max_seq_len=96,
                 decode_chunk=8), 24, 16)

smoke = gpt.GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                      num_heads=8, seq_len=256, remat=False,
                      compute_dtype=jnp.float32)
run("smoke 4L/256h (compute-dominated on CPU)", smoke,
    EngineConfig(slots=4, max_prompt_len=16, max_seq_len=64,
                 decode_chunk=8), 16, 8)
