"""Benchmark: GPT-2 355M-class training throughput on one chip, and
(``--mode serve``) continuous-batching serving throughput/latency over
the same model family.

Flagship config (BASELINE.md tracked config #4's model at single-chip
scale): full train step — bf16 forward/backward with remat, fused-Adam
Pallas sweep, loss scaling machinery engaged (identity for bf16) — i.e.
the whole SURVEY.md §3.2 per-iteration stack under one jit.

Baseline for ``vs_baseline``: the reference publishes no numbers
(BASELINE.md), so we use a derived A100 figure — apex-accelerated
Megatron-class GPT-2 355M at ~40% MFU on A100 bf16 (312 TFLOP/s peak):
0.4 * 312e12 / (6 * 355e6) ≈ 58.6k tokens/s/chip. vs_baseline =
measured / 58600.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp

from apex_tpu._capabilities import enable_compilation_cache

# repo-local persistent compile cache (JAX_COMPILATION_CACHE_DIR
# overrides; empty disables): warm starts skip the 20-40s compile
enable_compilation_cache()

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam

BASELINE_TOKENS_PER_SEC = 58600.0


def serve(telemetry_out=None):
    """Serving throughput/latency at a fixed seeded request trace: one
    JSON line with tokens/s, the TTFT-vs-steady-decode split, and a
    ``decode_chunk`` sweep (chunked device-side decode loop,
    ``gpt.decode_steps``) — the serving-side companion of the training
    number, trajectory-trackable per chunk setting.

    ``telemetry_out``: dump a telemetry-registry snapshot of the
    headline (chunk=8) trace, replayed instrumented AFTER the measured
    sweep so the throughput numbers stay flag-independent — ``"-"``
    embeds it in the JSON line under ``"telemetry"``, any other value
    writes that path."""
    import dataclasses

    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.scheduler import Scheduler
    from apex_tpu.telemetry.registry import Registry

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = gpt.GPTConfig(  # the training bench's 355M, decode form
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            seq_len=1024, remat=False, compute_dtype=jnp.bfloat16,
            attn_impl="flash", ln_impl="xla",
        )
        ecfg = EngineConfig(slots=8, max_prompt_len=64, max_seq_len=192)
        n_requests, max_tokens = 32, 64
    else:  # CPU smoke fallback so the harness always gets a line
        cfg = gpt.GPTConfig(
            vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
            seq_len=256, remat=False, compute_dtype=jnp.float32,
        )
        ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=32)
        n_requests, max_tokens = 8, 8

    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))

    def trace(seed0, n):
        reqs = []
        for i in range(n):
            p_len = 1 + (11 * i + 5) % ecfg.max_prompt_len
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(seed0 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"r{i}", prompt, max_tokens=max_tokens,
                                sampling=sp))
        return reqs

    sweep = {}
    tokens_by_chunk = {}
    for chunk in (1, 2, 4, 8):
        engine = Engine(cfg, params, mesh,
                        dataclasses.replace(ecfg, decode_chunk=chunk))
        # warmup: compile admit + step (and fill the persistent cache)
        warm = Scheduler(engine)
        for r in trace(9000, 2):
            warm.submit(r)
        warm.run_until_idle()
        sched = Scheduler(engine)
        for r in trace(100, n_requests):
            sched.submit(r)
        sched.run_until_idle()
        s = sched.summary()
        tokens_by_chunk[chunk] = {
            rid: c.tokens for rid, c in sched.completions.items()}
        sweep[str(chunk)] = {
            "tokens_per_sec": round(s["tokens_per_sec"], 1),
            "decode_tokens_per_sec": round(
                s.get("decode_tokens_per_sec", 0.0), 1),
            "ttft_mean_ms": round(s["ttft_mean_ms"], 2),
            "ttft_p99_ms": round(s["ttft_p99_ms"], 2),
            "token_latency_mean_ms": round(
                s["token_latency_mean_ms"], 3),
        }
    # the chunk knob must not change a single emitted token
    assert all(tokens_by_chunk[c] == tokens_by_chunk[1]
               for c in tokens_by_chunk), "chunk sweep token drift"
    if telemetry_out:
        # snapshot from a SEPARATE instrumented replay of the headline
        # (chunk=8) trace on the already-warm engine — the measured
        # sweep above stays uninstrumented, so the trajectory metric is
        # comparable whether or not this flag is passed
        registry = Registry()
        sched = Scheduler(engine, registry=registry)
        for r in trace(100, n_requests):
            sched.submit(r)
        sched.run_until_idle()
    head = sweep["8"]
    line = {
        "metric": "gpt2_355m_serve_tokens_per_sec_per_chip" if on_tpu
        else "gpt_serve_smoke_cpu_tokens_per_sec",
        "value": head["tokens_per_sec"],
        "unit": "tokens/s",
        "requests": n_requests,
        "slots": ecfg.slots,
        "decode_chunk": 8,
        # TTFT (admission/prefill) vs steady-decode split at the
        # headline chunk, then the whole sweep for trajectory tracking
        "ttft_mean_ms": head["ttft_mean_ms"],
        "ttft_p99_ms": head["ttft_p99_ms"],
        "decode_tokens_per_sec": head["decode_tokens_per_sec"],
        "token_latency_mean_ms": head["token_latency_mean_ms"],
        "chunk_sweep": sweep,
    }
    if telemetry_out == "-":
        line["telemetry"] = registry.to_dict()
    elif telemetry_out:
        with open(telemetry_out, "w") as f:
            json.dump(registry.to_dict(), f, indent=1, sort_keys=True)
        line["telemetry_out"] = telemetry_out
    print(json.dumps(line))


def main():
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = gpt.GPTConfig(  # GPT-2 355M
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            seq_len=1024, remat=True, ce_chunk=512,
            compute_dtype=jnp.bfloat16,
            # measured on v5e: Pallas flash (512x512 tiles, lane-packed
            # [b, s, hidden] layout — attn_layout="auto") beats both XLA
            # attention variants once the whole step is jitted; XLA-fused
            # LN beats the opaque Pallas LN call inside the layer scan;
            # pinning qkv/fc1 projections AND the flash kernel's (out,
            # lse) residuals (backward never re-runs the fwd attention
            # kernel) at the MXU-aligned b=16 beats every larger-batch
            # fuller-remat combination tried
            attn_impl="flash", ln_impl="xla", remat_policy="qkv_fc1_attn",
        )
        batch, steps = 16, 15
    else:  # CPU smoke fallback so the harness always gets a line
        cfg = gpt.GPTConfig(
            vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
            seq_len=256, remat=True, compute_dtype=jnp.bfloat16,
        )
        batch, steps = 4, 3

    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    # tree-layout Adam: moments mirror the (few, large, layer-stacked)
    # param leaves — no flat-packing copies, ~4 GB lower peak HBM
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-4, layout="tree"),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.seq_len), 0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)

    # warmup / compile; the float() fetch is the sync barrier throughout —
    # through the remote-device tunnel, block_until_ready can return at
    # dispatch time, a value fetch cannot
    state, m = step_fn(state, tok, tgt)
    _ = float(m["loss"])

    best = float("inf")
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step_fn(state, tok, tgt)
        _ = float(m["loss"])
        best = min(best, time.perf_counter() - t0)

    tokens_per_sec = batch * cfg.seq_len * steps / best
    print(json.dumps({
        "metric": "gpt2_355m_train_tokens_per_sec_per_chip" if on_tpu
        else "gpt_smoke_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("train", "serve"), default="train",
                    help="train (default): whole-step training "
                    "throughput; serve: continuous-batching decode "
                    "throughput + TTFT/latency at a fixed request trace")
    ap.add_argument("--telemetry-out", metavar="PATH", default=None,
                    help="serve mode: dump the telemetry-registry "
                    "snapshot of the headline run — '-' embeds it in "
                    "the JSON line, anything else writes that file")
    args = ap.parse_args()
    serve(telemetry_out=args.telemetry_out) if args.mode == "serve" \
        else main()
