"""Benchmark: GPT-2 355M-class training throughput on one chip, and
(``--mode serve``) continuous-batching serving throughput/latency over
the same model family.

Flagship config (BASELINE.md tracked config #4's model at single-chip
scale): full train step — bf16 forward/backward with remat, fused-Adam
Pallas sweep, loss scaling machinery engaged (identity for bf16) — i.e.
the whole SURVEY.md §3.2 per-iteration stack under one jit.

Baseline for ``vs_baseline``: the reference publishes no numbers
(BASELINE.md), so we use a derived A100 figure — apex-accelerated
Megatron-class GPT-2 355M at ~40% MFU on A100 bf16 (312 TFLOP/s peak):
0.4 * 312e12 / (6 * 355e6) ≈ 58.6k tokens/s/chip. vs_baseline =
measured / 58600.
"""

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp

from apex_tpu._capabilities import enable_compilation_cache

# repo-local persistent compile cache (JAX_COMPILATION_CACHE_DIR
# overrides; empty disables): warm starts skip the 20-40s compile
enable_compilation_cache()

from apex_tpu import mesh as mx
from apex_tpu.amp import ScalerConfig
from apex_tpu.models import gpt, training
from apex_tpu.optimizers import fused_adam

BASELINE_TOKENS_PER_SEC = 58600.0

#: stable trajectory keys for the BENCH_serve.json series (bumped per
#: PR so the per-line provenance is plottable without git archaeology)
BENCH_PR = 20
BENCH_LABEL = "durable-journal"

#: every BENCH_serve.json line must carry these, with these types —
#: the provenance triple that makes the series plottable without git
#: archaeology. Validated at append time (the PR-12 lesson upgraded
#: from convention to contract: a mode writing a key-drifted line now
#: fails ITS OWN run loudly instead of silently breaking the cross-PR
#: trajectory for whoever plots it next)
_TRAJ_REQUIRED = (("pr", int), ("label", str), ("metric", str))


def _validate_traj_row(row):
    for key, typ in _TRAJ_REQUIRED:
        if key not in row:
            raise ValueError(
                f"BENCH_serve.json line missing required key {key!r}: "
                f"{sorted(row)}")
        if not isinstance(row[key], typ) or (typ is str
                                             and not row[key]):
            raise ValueError(
                f"BENCH_serve.json line key {key!r} must be a "
                f"non-empty {typ.__name__}, got {row[key]!r}")
    if not any(k == "tokens_per_sec" or k.endswith("_tokens_per_sec")
               for k in row):
        raise ValueError(
            f"BENCH_serve.json line carries no *tokens_per_sec "
            f"throughput key: {sorted(row)}")


def _append_traj(*rows):
    """Append trajectory lines to BENCH_serve.json (one JSON object
    per line) — THE writer every serve mode shares, so the file's
    format cannot drift between modes. Every row is schema-checked
    first (:data:`_TRAJ_REQUIRED` + a throughput key); nothing is
    written unless ALL rows pass, so a drifted mode cannot half-append."""
    for row in rows:
        _validate_traj_row(row)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_serve.json")
    with open(path, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return os.path.basename(path)


def _smoke_headline():
    """The STANDARD serve-smoke trajectory fields, measured the same
    way every PR's line measures them: the CPU smoke config at the
    headline knobs (chunk=8, pipeline depth 2, batched bucketed
    admission) on the seeded burst trace, best-of-3. Every serve-mode
    BENCH_serve.json append carries one of these lines — the PR-12
    lesson: a mode that only writes its mode-specific metric breaks
    the cross-PR trajectory (`tokens_per_sec` et al. simply vanish
    from the series), so mode extras now ride as SEPARATE labeled
    lines next to an always-present standard smoke line."""
    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.scheduler import Scheduler

    cfg = gpt.GPTConfig(
        vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
        seq_len=256, remat=False, compute_dtype=jnp.float32)
    ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=32,
                        decode_chunk=8)
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))

    def trace():
        reqs = []
        for i in range(8):
            p_len = 1 + (11 * i + 5) % ecfg.max_prompt_len
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(100 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            stop = ([[(13 * i + 1) % cfg.vocab_size,
                      (13 * i + 2) % cfg.vocab_size]]
                    if i % 4 == 0 else None)
            reqs.append(Request(f"r{i}", prompt, max_tokens=8,
                                sampling=sp, stop=stop))
        return reqs

    with Engine(cfg, params, mesh, ecfg).warmup() as eng:
        best = None
        toks0 = None
        for _ in range(3):
            sched = Scheduler(eng, pipeline_depth=2)
            for r in trace():
                sched.submit(r)
            sched.run_until_idle()
            toks = {rid: c.tokens for rid, c in
                    sched.completions.items()}
            toks0 = toks0 or toks
            assert toks0 == toks, "smoke headline rerun drift"
            s = sched.summary()
            if best is None or s["tokens_per_sec"] > \
                    best["tokens_per_sec"]:
                best = s
        return {
            "metric": "gpt_serve_smoke_cpu_tokens_per_sec",
            "tokens_per_sec": round(best["tokens_per_sec"], 1),
            "decode_tokens_per_sec": round(
                best.get("decode_tokens_per_sec", 0.0), 1),
            "ttft_mean_ms": round(best["ttft_mean_ms"], 2),
            "cache_bytes_per_slot": eng.cache_bytes() // ecfg.slots,
        }


def chaos_smoke():
    """``--mode serve --chaos``: a seeded fault plan (one fault per
    engine seam) against the CPU-sized serve config — asserts the
    engine recovers without process death, every request completes,
    and requests untouched by the faults (all non-``error`` outcomes)
    emit bit-identical tokens to a fault-free run of the same trace.
    One JSON line."""
    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.resilience import (
        FaultPlan, FaultSpec, ResilienceConfig)
    from apex_tpu.serving.scheduler import Scheduler

    cfg = gpt.GPTConfig(
        vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
        seq_len=256, remat=False, compute_dtype=jnp.float32)
    ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=32,
                        decode_chunk=2)
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))

    def trace():
        reqs = []
        for i in range(10):
            p_len = 1 + (5 * i + 3) % ecfg.max_prompt_len
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(400 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"r{i}", prompt, max_tokens=8,
                                sampling=sp))
        return reqs

    def run(plan):
        # context-managed: chaos engines are created per side — the
        # close() releases the sentinel listener, host state survives
        with Engine(cfg, params, mesh, ecfg,
                    fault_plan=plan).warmup() as eng:
            sched = Scheduler(eng, pipeline_depth=2, resilience=(
                ResilienceConfig(backoff_base_s=0.002)))
            for r in trace():
                sched.submit(r)
            sched.run_until_idle()
            return sched

    # one fault at every seam: raised errors at admit + dispatch, a
    # NaN batch + a (0 s) hang at fetch — seeded indices, exact rerun
    plan = FaultPlan([
        FaultSpec("admit", 1, "error"),
        FaultSpec("dispatch", 3, "error"),
        FaultSpec("fetch", 5, "nan", slots=(1,)),
        FaultSpec("fetch", 8, "hang", hang_s=0.0),
    ])
    chaotic = run(plan)
    clean = run(None)
    assert len(chaotic.completions) == 10, "chaos run lost requests"
    errored = {rid for rid, c in chaotic.completions.items()
               if c.finish_reason == "error"}
    drift = [rid for rid, c in chaotic.completions.items()
             if rid not in errored
             and c.tokens != clean.completions[rid].tokens]
    assert not drift, f"token drift for unaffected requests: {drift}"
    s = chaotic.summary()
    print(json.dumps({
        "metric": "gpt_serve_chaos_smoke",
        "value": 1.0,
        "unit": "pass",
        "requests": 10,
        "faults_fired": len(plan.injected),
        "rebuilds": s["rebuilds"],
        "retries": s["retries"],
        "errored": len(errored),
        "token_drift": 0,
        "health_state": s["health_state"],
    }))


def fleet_smoke():
    """``--mode serve --fleet``: the failover A/B — a fleet of 2
    replicas with a deterministic kill-one-mid-burst drill
    (``FleetFaultPlan.kill``) vs a clean single replica on the same
    trace. Asserts the victim fails terminally, its interrupted
    requests fail over, and EVERY stream is bit-identical to the
    clean run (zero duplicate, zero lost tokens). Appends TWO
    BENCH_serve.json lines: the standard smoke line (cross-PR
    comparable) and the fleet extras under their own metric. One JSON
    line printed."""
    import time as _time

    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.fleet import Router
    from apex_tpu.serving.resilience import (
        FleetFaultPlan, ResilienceConfig)
    from apex_tpu.serving.scheduler import Scheduler

    cfg = gpt.GPTConfig(
        vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
        seq_len=256, remat=False, compute_dtype=jnp.float32)
    ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=32,
                        decode_chunk=2)
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))

    def trace():
        reqs = []
        for i in range(12):
            p_len = 1 + (5 * i + 3) % ecfg.max_prompt_len
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(500 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"r{i}", prompt, max_tokens=8,
                                sampling=sp))
        return reqs

    # clean single-replica reference
    with Engine(cfg, params, mesh, ecfg).warmup() as eng:
        sched = Scheduler(eng, pipeline_depth=2)
        for r in trace():
            sched.submit(r)
        t0 = _time.perf_counter()
        sched.run_until_idle()
        single_wall = _time.perf_counter() - t0
        clean = {rid: c.tokens for rid, c in sched.completions.items()}
        single_tokens = sched.summary()["tokens_emitted"]

    # fleet of 2, replica 1 killed mid-burst (retry headroom so the
    # per-request retry bound can't drain the victim before the
    # rebuild-storm counter crosses terminal — see FleetFaultPlan.kill)
    plans = FleetFaultPlan.kill(1, 2, at=2)
    scheds = [Scheduler(
        Engine(cfg, params, mesh, ecfg, fault_plan=plans[i]).warmup(),
        pipeline_depth=2,
        resilience=ResilienceConfig(max_retries=8,
                                    backoff_base_s=0.002,
                                    # a throttled host's >30s chunk
                                    # would breaker-evict the victim
                                    # before the drill terminates it
                                    watchdog_timeout_s=600.0))
        for i in range(2)]
    with Router(scheds) as router:
        for r in trace():
            router.submit(r)
        t0 = _time.perf_counter()
        router.run_until_idle()
        fleet_wall = _time.perf_counter() - t0
        s = router.summary()
        assert len(router.completions) == 12, "fleet run lost requests"
        assert scheds[1].health.state == "failed", \
            "kill drill did not terminate replica 1"
        assert s["failed_over_requests"] > 0, "nothing failed over"
        drift = [rid for rid, c in router.completions.items()
                 if c.tokens != clean[rid]]
        assert not drift, f"failover token drift: {drift}"
        fleet_tokens = s["tokens_emitted"]

    line = {
        "metric": "gpt_serve_fleet_failover",
        "value": 1.0,
        "unit": "pass",
        "requests": 12,
        "faults_fired": len(plans.injected),
        "failover_waves": s["failover_waves"],
        "failed_over_requests": s["failed_over_requests"],
        "incidents": s["incidents"],
        "token_drift": 0,
        "fleet_tokens_per_sec": round(fleet_tokens / fleet_wall, 1),
        "single_tokens_per_sec": round(single_tokens / single_wall, 1),
    }
    # BOTH lines: the standard smoke line (the cross-PR comparable
    # series — tokens/s, TTFT, cache bytes) plus the fleet extras as
    # their own labeled line, so a mode-specific metric can never
    # break the trajectory again (the PR-12 regression)
    smoke = _smoke_headline()
    line["bench_out"] = _append_traj(
        {"pr": BENCH_PR, "label": BENCH_LABEL, **smoke},
        {
            "pr": BENCH_PR,
            "label": BENCH_LABEL,
            "metric": line["metric"],
            "fleet_tokens_per_sec": line["fleet_tokens_per_sec"],
            "single_tokens_per_sec": line["single_tokens_per_sec"],
            "failed_over_requests": s["failed_over_requests"],
            "token_drift": 0,
        })
    print(json.dumps(line))


def oversub_smoke():
    """``--mode serve --oversub``: the KV-oversubscription A/B — a
    mixed idle-heavy trace (conversations go idle mid-stream, the
    pause/park regime host swap exists for) driven through a
    host-swap engine over a deliberately small page pool, vs the SAME
    trace and pool hard-capped (no host tier: an idle conversation
    either squats on its HBM pages or waits in the queue holding no
    state). Headline: peak conversations RESIDENT per chip (active +
    parked-with-state) vs the hard-capped pool's peak — the
    oversubscription gain; acceptance wants >= 4x. Every stream
    (greedy AND sampled) must be bit-identical to an uninterrupted
    run, and a paired swap-vs-recompute resume A/B prices the
    ``resume_policy`` decision. Appends the standard smoke line plus
    the oversub extras to BENCH_serve.json. One JSON line printed."""
    import time as _time

    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.scheduler import Scheduler

    cfg = gpt.GPTConfig(
        vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
        seq_len=256, remat=False, compute_dtype=jnp.float32)
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    # page pool sized to ~3 worst-case conversations (+1 sink): each
    # request pins <= 3 pages (prompt <= 16 + budget 8 over 8-token
    # pages), so the hard-capped side can never hold more than 3
    # conversations' KV state at once — the floor the host tier lifts
    base = dict(slots=4, max_prompt_len=16, max_seq_len=32,
                decode_chunk=2, page_size=8, num_pages=10)
    n_convs = 16

    def trace():
        reqs = []
        for i in range(n_convs):
            p_len = 1 + (11 * i + 5) % base["max_prompt_len"]
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(950 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"r{i}", prompt, max_tokens=8,
                                sampling=sp))
        return reqs

    # uninterrupted reference: same numerics (paged, same page size),
    # ample pool — the oracle every swapped/preempted/resumed stream
    # must match bit-for-bit
    ref_kw = dict(base, num_pages=0)
    with Engine(cfg, params, mesh,
                EngineConfig(**ref_kw)).warmup() as eng:
        sched = Scheduler(eng, max_queue=2 * n_convs)
        for r in trace():
            sched.submit(r)
        sched.run_until_idle()
        ref = {rid: c.tokens for rid, c in sched.completions.items()}

    def resident(sched):
        # conversations holding KV state on/off chip: active slots +
        # parked payloads/snapshots (queued requests hold nothing)
        return len(sched.active) + len(sched.parked_requests)

    def idle_heavy_drive(sched, pauses):
        """Submit one conversation per wave, tick a couple of chunks,
        then park every still-running stream (its user went idle) —
        returns (peak resident, peak parked) counts."""
        peak = peak_parked = 0
        for r in trace():
            sched.submit(r)
            for _ in range(2):
                sched.step()
                peak = max(peak, resident(sched))
            if pauses:
                for rid in sorted(a.request.request_id
                                  for a in sched.active.values()):
                    sched.pause(rid)
                peak = max(peak, resident(sched))
                peak_parked = max(peak_parked,
                                  len(sched.parked_requests))
        return peak, peak_parked

    # oversubscribed side: host tier + preemption on, same tiny pool
    eng_o = Engine(cfg, params, mesh, EngineConfig(
        **base, host_swap=True, resume_policy="auto")).warmup()
    sen0 = eng_o.recompile_sentinel()
    s_o = Scheduler(eng_o, max_queue=2 * n_convs, preempt=True)
    t0 = _time.perf_counter()
    peak_over, peak_parked = idle_heavy_drive(s_o, pauses=True)
    for rid in list(s_o.parked_requests):
        s_o.resume(rid)
    s_o.run_until_idle()
    over_wall = _time.perf_counter() - t0
    over = {rid: c.tokens for rid, c in s_o.completions.items()}
    summ_o = s_o.summary()
    assert eng_o.recompile_sentinel() == sen0, \
        "oversub run recompiled — swap variants missed warmup"
    eng_o.close()

    # hard-capped side: same pool, no host tier — a paused
    # conversation is impossible, so the drive just backpressures
    with Engine(cfg, params, mesh,
                EngineConfig(**base)).warmup() as eng_c:
        s_c = Scheduler(eng_c, max_queue=2 * n_convs)
        peak_cap, _ = idle_heavy_drive(s_c, pauses=False)
        s_c.run_until_idle()
        capped = {rid: c.tokens for rid, c in s_c.completions.items()}

    # zero drift, both sides, greedy and sampled alike
    drift = sorted(rid for rid in ref
                   if over.get(rid) != ref[rid]
                   or capped.get(rid) != ref[rid])
    assert not drift, f"oversubscription token drift: {drift}"
    gain = peak_over / max(peak_cap, 1)
    assert gain >= 4.0, (
        f"oversubscription gain {gain:.2f}x < 4x "
        f"(resident {peak_over} vs hard-capped {peak_cap})")

    # paired swap-vs-recompute resume A/B on an ample pool (no
    # preemption noise): park the whole wave mid-stream, then time
    # resume -> drain under each policy — the decode work is
    # identical, so the pair prices exactly swap-in scatter vs
    # replay-from-snapshot. Value-fetch synced (run_until_idle
    # fetches every completion); paired per round, median reported.
    engines = {
        pol: Engine(cfg, params, mesh, EngineConfig(
            **dict(base, num_pages=0), host_swap=True,
            resume_policy=pol)).warmup()
        for pol in ("swap", "recompute")}
    walls = {"swap": [], "recompute": []}
    ratios = []
    ab_toks = {}
    for rnd in range(5):
        round_wall = {}
        for pol in _ab_order(rnd, ("swap", "recompute")):
            sched = Scheduler(engines[pol], max_queue=2 * n_convs)
            for r in trace()[:6]:
                sched.submit(r)
            for _ in range(2):
                sched.step()
            for rid in sorted(a.request.request_id
                              for a in sched.active.values()):
                sched.pause(rid)
            assert sched.parked_requests, \
                "resume A/B parked nothing — pause came too late"
            t0 = _time.perf_counter()
            for rid in list(sched.parked_requests):
                sched.resume(rid)
            sched.run_until_idle()
            round_wall[pol] = _time.perf_counter() - t0
            walls[pol].append(round_wall[pol])
            toks = {rid: c.tokens for rid, c in
                    sched.completions.items()}
            ab_toks.setdefault(pol, toks)
            assert ab_toks[pol] == toks, f"resume ab {pol} rerun drift"
            assert all(toks[rid] == ref[rid] for rid in toks), \
                f"resume ab {pol} drift vs uninterrupted"
        ratios.append(round_wall["recompute"]
                      / max(round_wall["swap"], 1e-9))
    for e in engines.values():
        e.close()

    line = {
        "metric": "gpt_serve_oversub",
        "value": round(gain, 3),
        "unit": "x_resident_conversations",
        "conversations": n_convs,
        "num_pages": base["num_pages"],
        "peak_resident_oversub": peak_over,
        "peak_resident_capped": peak_cap,
        "parked_conversations_per_chip": peak_parked,
        "pauses": summ_o["pauses"],
        "preemptions": summ_o["preemptions"],
        "swap_resumes": summ_o["swap_resumes"],
        "recompute_resumes": summ_o["recompute_resumes"],
        "oversub_tokens_per_sec": round(
            summ_o["tokens_emitted"] / over_wall, 1),
        "swap_resume_ms": round(1e3 * _median(walls["swap"]), 2),
        "recompute_resume_ms": round(
            1e3 * _median(walls["recompute"]), 2),
        "recompute_vs_swap_ratio": round(_median(ratios), 3),
        "token_drift": 0,
    }
    smoke = _smoke_headline()
    line["bench_out"] = _append_traj(
        {"pr": BENCH_PR, "label": BENCH_LABEL, **smoke},
        {
            "pr": BENCH_PR,
            "label": BENCH_LABEL,
            "metric": line["metric"],
            "oversub_tokens_per_sec": line["oversub_tokens_per_sec"],
            "parked_conversations_per_chip": line[
                "parked_conversations_per_chip"],
            "resident_gain": line["value"],
            "recompute_vs_swap_ratio": line["recompute_vs_swap_ratio"],
            "token_drift": 0,
        })
    print(json.dumps(line))


def _api_wire_load(engine, reqs, inproc_tokens, vocab_size):
    """``--mode serve --api``: drive the burst trace through a LIVE
    local ``apex_tpu.serving.api`` server — one SSE streaming
    connection per request, all launched at t=0 — and report served
    tok/s + client-measured TTFT next to the in-process numbers.
    Asserts zero token drift: every wire stream must be bit-identical
    to the in-process engine's stream for the same request (replay/
    suppression guarantees extend to the wire)."""
    import http.client
    import threading
    import time as _time

    from apex_tpu.serving.api import ApiServer, ByteTokenizer
    from apex_tpu.serving.scheduler import Scheduler

    sched = Scheduler(engine, max_queue=max(256, len(reqs)),
                      pipeline_depth=2)
    server = ApiServer(sched, ByteTokenizer(vocab_size)).start()
    n = len(reqs)
    tokens = [None] * n
    ttft = [0.0] * n
    done_at = [0.0] * n
    errors = []

    def worker(i, r):
        try:
            body = {"prompt": list(r.prompt), "max_tokens": r.max_tokens,
                    "stream": True, "return_token_ids": True}
            if r.sampling.temperature > 0:
                body.update(temperature=r.sampling.temperature,
                            top_k=r.sampling.top_k,
                            top_p=r.sampling.top_p,
                            seed=r.sampling.seed)
            if r.stop:
                body["stop_token_ids"] = [list(s) for s in r.stop]
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.port, timeout=600)
            t0 = _time.perf_counter()
            conn.request("POST", "/v1/completions", json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()[:200]
            toks, first = [], None
            while True:
                line = resp.readline()
                if not line:
                    break
                if not line.startswith(b"data: "):
                    continue
                if line.strip() == b"data: [DONE]":
                    break
                chunk = json.loads(line[len(b"data: "):])
                for ch in chunk.get("choices", ()):
                    ids = ch.get("token_ids")
                    if ids:
                        if first is None:
                            first = _time.perf_counter()
                        toks.extend(ids)
            conn.close()
            tokens[i] = toks
            ttft[i] = (first or _time.perf_counter()) - t0
            done_at[i] = _time.perf_counter()
        except Exception as e:  # surfaced after join
            errors.append((i, repr(e)))

    t_start = _time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i, r))
               for i, r in enumerate(reqs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(done_at) - t_start
    server.stop()
    assert not errors, f"wire load failures: {errors[:3]}"
    drift = [r.request_id for i, r in enumerate(reqs)
             if tokens[i] != inproc_tokens[r.request_id]]
    assert not drift, f"wire-vs-inprocess token drift: {drift}"
    total = sum(len(t) for t in tokens)
    return {
        "served_tokens_per_sec": round(total / wall, 1),
        "ttft_mean_ms": round(1e3 * sum(ttft) / n, 2),
        "ttft_p99_ms": round(1e3 * sorted(ttft)[int(0.99 * (n - 1))], 2),
        "requests": n,
        "tokens": total,
        "token_drift": 0,
    }


def crash_smoke():
    """``--mode serve --crash``: the durable-journal A/B + recovery
    drill — the SAME seeded burst trace run with the write-ahead
    request journal on (``fsync="batch"``) vs off, paired per
    interleaved round with the median wall ratio reported (the
    durability tax must live inside the established noise band), plus
    an in-process crash-at-the-fsync-boundary drill: run the journaled
    side partway, drop the device state (``rebuild_slots`` — the
    warm-restart regime, process alive but engine state gone), then
    ``recover_scheduler`` from the journal and drain — every recovered
    stream (greedy AND sampled) must be bit-identical to an
    uninterrupted run, with zero recompiles. Reports
    ``recovery_time_ms`` (scan + replay + resubmit, value-fetch
    synced by the drained completions) and ``journal_fsync_ms`` (the
    victim's total fsync stall). Appends the standard smoke line plus
    the crash extras to BENCH_serve.json. One JSON line printed."""
    import shutil
    import tempfile
    import time as _time

    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.journal import Journal, recover_scheduler
    from apex_tpu.serving.scheduler import Scheduler

    cfg = gpt.GPTConfig(
        vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
        seq_len=256, remat=False, compute_dtype=jnp.float32)
    ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=32,
                        decode_chunk=2)
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    n = 12

    def trace():
        reqs = []
        for i in range(n):
            p_len = 1 + (7 * i + 3) % ecfg.max_prompt_len
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(1200 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"r{i}", prompt, max_tokens=8,
                                sampling=sp))
        return reqs

    workdir = tempfile.mkdtemp(prefix="apex_crash_smoke_")
    try:
        with Engine(cfg, params, mesh, ecfg).warmup() as eng:
            sen0 = eng.recompile_sentinel()

            def run(journal_dir):
                j = (Journal(journal_dir, fsync="batch")
                     if journal_dir else None)
                sched = Scheduler(eng, max_queue=2 * n, journal=j)
                for r in trace():
                    sched.submit(r)
                t0 = _time.perf_counter()
                sched.run_until_idle()
                wall = _time.perf_counter() - t0
                if j is not None:
                    j.close()
                toks = {rid: c.tokens for rid, c in
                        sched.completions.items()}
                return toks, wall, sched

            # uninterrupted journal-free reference: the oracle both
            # the A/B sides and every recovered stream must match
            ref, _, _ = run(None)

            # paired journal-on/off A/B: same engine, same trace,
            # alternating side order, median per-round ratio
            walls = {"on": [], "off": []}
            ratios = []
            fsync_ms = 0.0
            for rnd in range(5):
                round_wall = {}
                for side in _ab_order(rnd, ("on", "off")):
                    jd = (os.path.join(workdir, f"ab{rnd}")
                          if side == "on" else None)
                    toks, wall, sched = run(jd)
                    assert toks == ref, f"crash ab {side} token drift"
                    round_wall[side] = wall
                    walls[side].append(wall)
                    if side == "on":
                        fsync_ms = max(
                            fsync_ms,
                            1e3 * sched.summary()["journal_fsync_s"])
                        shutil.rmtree(jd)
                ratios.append(round_wall["on"]
                              / max(round_wall["off"], 1e-9))
            overhead = _median(ratios)
            assert 0.74 <= overhead <= 1.23, (
                f"journal overhead ratio {overhead:.3f} outside the "
                f"paired-A/B noise band (0.74-1.23) — the durability "
                f"tax is real, price it in DESIGN.md")

            # crash drill: journaled run partway, device state dropped
            # at the fsync boundary, then recover from the journal
            jd = os.path.join(workdir, "drill")
            j = Journal(jd, fsync="batch")
            victim = Scheduler(eng, max_queue=2 * n, journal=j)
            for r in trace():
                victim.submit(r)
            for _ in range(4):
                victim.step()
            prior = {rid: c.tokens for rid, c in
                     victim.completions.items()}
            drill_fsync_ms = 1e3 * j.fsync_s
            j.close()
            eng.rebuild_slots()

            t0 = _time.perf_counter()
            sched2, report = recover_scheduler(
                jd, lambda: eng, max_queue=2 * n)
            recovery_ms = 1e3 * (_time.perf_counter() - t0)
            sched2.run_until_idle()
            sched2.journal.close()
            merged = dict(prior)
            merged.update({rid: c.tokens for rid, c in
                           sched2.completions.items()})
            drift = sorted(rid for rid in ref
                           if merged.get(rid) != ref[rid])
            assert not drift, f"crash recovery token drift: {drift}"
            assert eng.recompile_sentinel() == sen0, \
                "crash drill recompiled — recovery missed warmup"

            line = {
                "metric": "gpt_serve_crash",
                "value": round(overhead, 3),
                "unit": "x_journal_overhead",
                "requests": n,
                "journal_overhead_ratio": round(overhead, 3),
                "journaled_tokens_per_sec": round(
                    n * 8 / _median(walls["on"]), 1),
                "unjournaled_tokens_per_sec": round(
                    n * 8 / _median(walls["off"]), 1),
                "journal_fsync_ms": round(max(fsync_ms,
                                              drill_fsync_ms), 3),
                "recovery_time_ms": round(recovery_ms, 2),
                "recovered_requests": report.requests,
                "completed_before_crash": len(prior),
                "token_drift": 0,
            }
        smoke = _smoke_headline()
        line["bench_out"] = _append_traj(
            {"pr": BENCH_PR, "label": BENCH_LABEL, **smoke},
            {
                "pr": BENCH_PR,
                "label": BENCH_LABEL,
                "metric": line["metric"],
                "journal_overhead_ratio": line["journal_overhead_ratio"],
                "journaled_tokens_per_sec": line[
                    "journaled_tokens_per_sec"],
                "recovery_time_ms": line["recovery_time_ms"],
                "journal_fsync_ms": line["journal_fsync_ms"],
                "token_drift": 0,
            })
        print(json.dumps(line))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _ab_order(rnd, sides):
    """Paired-A/B side order for round ``rnd``: alternates round to
    round — a FIXED order lets a systematic first-runner/second-runner
    effect survive even paired per-round ratios (the PR-10 flightrec
    1.334 lesson)."""
    return sides if rnd % 2 == 0 else tuple(reversed(sides))


def _median(xs):
    """The paired-A/B ratio reducer: middle of the sorted per-round
    ratios (shared by every paired A/B so the convention can never
    diverge between them)."""
    return sorted(xs)[len(xs) // 2]


def serve(telemetry_out=None, api=False):
    """Serving throughput/latency at a fixed seeded BURST trace (every
    request arrives at t=0 — the admission-pressure regime batched
    admission exists for): one JSON line with tokens/s, the
    TTFT-vs-steady-decode split, a ``decode_chunk`` sweep, a
    pipelined-vs-serial loop A/B, a bucketed-vs-flat admission
    A/B, a paged-vs-contiguous KV-cache A/B (cache bytes pinned per
    active token on a mixed-length trace — the fragmentation-free
    capacity gain — plus steady-decode parity), a chunked-prefill A/B
    (short-stream TTFT inflation from one long admission, monolithic
    vs interleaved), a flight-recorder on/off A/B (the always-on
    black box must cost nothing: overhead ratio + events/s + atomic
    bundle-write latency), and a self-tuning A/B (the serving.tuner
    control plane vs every fixed (chunk, depth) corner on a SHIFTING
    burst trace — decode-heavy phase, then a short-request admission
    flood — reported as the paired-median ratio vs the best fixed
    corner), and a multi-tenant A/B (adapter-pool overhead on base
    traffic, plus a contended three-tenant trace at skewed weights
    with two registered LoRA adapters: mid-flood weighted fairness
    ratio, WFQ-vs-FIFO token-drift assert, and a rate-limited-tenant
    rerun whose 429s leave other tenants' streams bit-identical).
    A/B ratios are PAIRED per interleaved
    round with the median reported (independent per-side best-of-N
    let host drift land asymmetrically — the PR-10 flightrec line's
    1.334 lesson), and a sweep-WIDE token-drift assert pins every
    configuration to bit-identical per-request streams. Every 4th
    request
    carries a stop sequence (host-side tail match, trimmed emission),
    so the sweep also pins stop handling chunk/pipeline-invariant.

    ``api=True`` (``--api``): additionally drive the SAME burst trace
    through a live ``apex_tpu.serving.api`` HTTP server — one SSE
    streaming connection per request — reporting wire-level served
    tok/s + client-measured TTFT next to the in-process numbers, and
    asserting ZERO token drift between the wire stream and the
    in-process engine (the wire-realism oracle).

    ``telemetry_out``: dump a telemetry-registry snapshot of the
    headline (chunk=8, pipelined) trace, replayed instrumented AFTER
    the measured sweep so the throughput numbers stay flag-independent
    — ``"-"`` embeds it in the JSON line under ``"telemetry"``, any
    other value writes that path."""
    import dataclasses

    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.scheduler import Scheduler
    from apex_tpu.telemetry.registry import Registry

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = gpt.GPTConfig(  # the training bench's 355M, decode form
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            seq_len=1024, remat=False, compute_dtype=jnp.bfloat16,
            attn_impl="flash", ln_impl="xla",
        )
        ecfg = EngineConfig(slots=8, max_prompt_len=64, max_seq_len=192)
        n_requests, max_tokens = 32, 64
    else:  # CPU smoke fallback so the harness always gets a line
        cfg = gpt.GPTConfig(
            vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
            seq_len=256, remat=False, compute_dtype=jnp.float32,
        )
        ecfg = EngineConfig(slots=4, max_prompt_len=16, max_seq_len=32)
        n_requests, max_tokens = 8, 8

    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    params = gpt.init(cfg, jax.random.PRNGKey(0))

    def trace(seed0, n, vocab=None, mpl=None, mt=None):
        reqs = []
        for i in range(n):
            p_len = 1 + (11 * i + 5) % (mpl or ecfg.max_prompt_len)
            v = vocab or cfg.vocab_size
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(seed0 + i), (p_len,), 0, v)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            # every 4th request: a stop sequence on the streamed tail
            # (fires or not deterministically; either way the sweep's
            # bit-identical assert pins it chunk/pipeline-invariant)
            stop = ([[(13 * i + 1) % v, (13 * i + 2) % v]]
                    if i % 4 == 0 else None)
            reqs.append(Request(f"r{i}", prompt,
                                max_tokens=mt or max_tokens, sampling=sp,
                                stop=stop))
        return reqs

    def run(engine, reqs, **sched_kw):
        sched = Scheduler(engine, **sched_kw)
        for r in reqs:  # burst arrival: the whole trace at t=0
            sched.submit(r)
        sched.run_until_idle()
        return ({rid: c.tokens for rid, c in sched.completions.items()},
                sched.summary())

    def fmt(s):
        return {
            "tokens_per_sec": round(s["tokens_per_sec"], 1),
            "decode_tokens_per_sec": round(
                s.get("decode_tokens_per_sec", 0.0), 1),
            "ttft_mean_ms": round(s["ttft_mean_ms"], 2),
            "ttft_p99_ms": round(s["ttft_p99_ms"], 2),
            "token_latency_mean_ms": round(
                s["token_latency_mean_ms"], 3),
            "admit_dispatches": s["admit_dispatches"],
        }

    # every configuration measured below must emit identical streams;
    # single runs on this class of host invert comparisons through
    # noise, so every number is a best-of-reps and the A/Bs interleave
    # their two sides so noise hits both alike
    reps = 3 if not on_tpu else 2
    tokens_by_cfg = {}

    def measure_ab(sides):
        """Interleave the sides' reps — one rep of each per round,
        order ALTERNATING round to round (a fixed order lets a
        systematic first-runner/second-runner effect survive even
        paired ratios) — and return each side's best summary."""
        best = {}
        for rnd in range(reps):
            for name, engine, kw in _ab_order(rnd, tuple(sides)):
                toks, s = run(engine, trace(100, n_requests), **kw)
                if name not in tokens_by_cfg:
                    tokens_by_cfg[name] = toks
                assert tokens_by_cfg[name] == toks, f"{name} rerun drift"
                if name not in best or s["tokens_per_sec"] > \
                        best[name]["tokens_per_sec"]:
                    best[name] = s
        return best

    def measure(name, engine, **kw):
        return measure_ab([(name, engine, kw)])[name]

    sweep = {}
    for chunk in (1, 2, 4, 8):
        engine = Engine(cfg, params, mesh,
                        dataclasses.replace(ecfg, decode_chunk=chunk))
        engine.warmup()  # compile every (bucket, k) admission variant
        sweep[str(chunk)] = fmt(measure(f"chunk{chunk}", engine,
                                        pipeline_depth=2))
        if chunk != 8:
            engine.close()  # the chunk=8 engine rides on below
    head = sweep["8"]
    # the two admission/loop A/Bs ride the warm chunk=8 engine, same
    # burst, sides interleaved: pipelined (depth 2, batched admission)
    # vs serial (depth 1 + one-request admits — the pre-pipeline loop)
    # vs flat admission (one bucket at max_prompt_len, k=1 only — the
    # pre-bucketing path — under the pipelined loop)
    flat_eng = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, decode_chunk=8,
        prompt_buckets=(ecfg.max_prompt_len,), admit_batch_sizes=(1,)))
    flat_eng.warmup()
    ab = measure_ab([
        ("pipelined8", engine, dict(pipeline_depth=2)),
        ("serial", engine, dict(pipeline_depth=1, max_admit_batch=1)),
        ("flat_admission", flat_eng, dict(pipeline_depth=2)),
    ])
    s_pipe, s_serial, s_flat = (ab["pipelined8"], ab["serial"],
                                ab["flat_admission"])
    pipeline_ab = {
        "serial": fmt(s_serial),
        "pipelined": fmt(s_pipe),
        "speedup": round(s_pipe["tokens_per_sec"]
                         / s_serial["tokens_per_sec"], 3),
    }
    bucket_ab = {
        "flat": fmt(s_flat),
        "bucketed_batched": fmt(s_pipe),
        "ttft_speedup": round(s_flat["ttft_mean_ms"]
                              / max(s_pipe["ttft_mean_ms"], 1e-9), 3),
    }
    flat_eng.close()
    if not on_tpu:
        # the acceptance A/B shape: the dispatch-dominated 1L/32h CPU
        # probe (DESIGN.md "Decode performance") at an admission-heavy
        # burst — the CPU proxy for the chip's tunnel-latency regime,
        # where the pipeline and batched admission matter most. The
        # baseline engine+loop is the PRE-PIPELINE path verbatim: one
        # flat bucket at max_prompt_len, k=1 admits, serial depth-1
        # loop. Interleaved best-of-5 so host noise hits both alike.
        pcfg = gpt.GPTConfig(
            vocab_size=256, hidden_size=32, num_layers=1, num_heads=2,
            seq_len=128, remat=False, compute_dtype=jnp.float32)
        pparams = gpt.init(pcfg, jax.random.PRNGKey(0))
        pecfg = EngineConfig(slots=4, max_prompt_len=32, max_seq_len=96,
                             decode_chunk=8)
        new_eng = Engine(pcfg, pparams, mesh, pecfg).warmup()
        old_eng = Engine(pcfg, pparams, mesh, dataclasses.replace(
            pecfg, prompt_buckets=(32,),
            admit_batch_sizes=(1,))).warmup()
        ptrace = lambda: trace(300, 24, vocab=pcfg.vocab_size, mpl=32,
                               mt=16)
        best = {"serial": None, "pipelined": None}
        ptoks = {}
        for _ in range(7):
            t, s = run(old_eng, ptrace(), pipeline_depth=1,
                       max_admit_batch=1)
            ptoks.setdefault("serial", t)
            assert ptoks["serial"] == t, "probe serial drift"
            if best["serial"] is None or s["tokens_per_sec"] > \
                    best["serial"]["tokens_per_sec"]:
                best["serial"] = s
            t, s = run(new_eng, ptrace(), pipeline_depth=2)
            ptoks.setdefault("pipelined", t)
            assert ptoks["pipelined"] == t, "probe pipelined drift"
            if best["pipelined"] is None or s["tokens_per_sec"] > \
                    best["pipelined"]["tokens_per_sec"]:
                best["pipelined"] = s
        assert ptoks["serial"] == ptoks["pipelined"], "probe token drift"
        line_probe = {
            "serial_tokens_per_sec": round(
                best["serial"]["tokens_per_sec"], 1),
            "pipelined_tokens_per_sec": round(
                best["pipelined"]["tokens_per_sec"], 1),
            "speedup": round(best["pipelined"]["tokens_per_sec"]
                             / best["serial"]["tokens_per_sec"], 3),
            "serial_ttft_mean_ms": round(
                best["serial"]["ttft_mean_ms"], 2),
            "pipelined_ttft_mean_ms": round(
                best["pipelined"]["ttft_mean_ms"], 2),
        }
        new_eng.close()
        old_eng.close()
    # KV-cache capacity A/B #1 — quantized cache: int8 storage vs the
    # compute-dtype cache on the warm chunk=8 trace (interleaved
    # best-of-reps). Cache bytes per slot is the headline (the
    # throughput ceiling under heavy traffic); steady decode rides
    # along. Quantization CHANGES numerics, so the int8 side is
    # excluded from the sweep-wide bit-parity assert — its own rerun
    # stability is still pinned by measure_ab.
    cfg_q = dataclasses.replace(cfg, kv_cache_dtype="int8")
    eng_q = Engine(cfg_q, params, mesh,
                   dataclasses.replace(ecfg, decode_chunk=8))
    eng_q.warmup()
    kv_sides = measure_ab([
        ("kv_int8", eng_q, dict(pipeline_depth=2)),
        ("kv_base", engine, dict(pipeline_depth=2)),
    ])
    bytes_q, bytes_b = eng_q.cache_bytes(), engine.cache_bytes()
    kv_ab = {
        "base_cache_bytes_per_slot": bytes_b // ecfg.slots,
        "int8_cache_bytes_per_slot": bytes_q // ecfg.slots,
        "bytes_ratio": round(bytes_b / bytes_q, 3),
        "base_decode_tokens_per_sec": round(
            kv_sides["kv_base"].get("decode_tokens_per_sec", 0.0), 1),
        "int8_decode_tokens_per_sec": round(
            kv_sides["kv_int8"].get("decode_tokens_per_sec", 0.0), 1),
    }
    eng_q.close()

    # KV-cache capacity A/B #2 — shared-prefix reuse: every request
    # shares one long pooled template (half the prompt); the hit side
    # admits by compiled gather + tail-only prefill at the TAIL
    # bucket, the cold side full-prefills at the full prompt bucket.
    # Both sides run k=1 admissions (max_admit_batch=1) so the number
    # measured is PER-ADMISSION latency (TTFT), not the k-ladder's
    # amortisation — prefix hits ride k=1 extend programs, and letting
    # the cold side batch would compare different dispatch counts.
    # Token streams must be BIT-identical (prefix reuse is an
    # admission-cost play, not a numerics play).
    mpl_p = min(2 * ecfg.max_prompt_len, cfg.seq_len // 2)
    ecfg_p = dataclasses.replace(
        ecfg, decode_chunk=8, max_prompt_len=mpl_p,
        max_seq_len=mpl_p + 16)
    tlen = mpl_p // 2
    template = [int(t) for t in jax.random.randint(
        jax.random.PRNGKey(900), (tlen,), 0, cfg.vocab_size)]
    eng_pref = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg_p, prefix_pool_slots=1))
    eng_pref.warmup()
    eng_pref.register_prefix(template)
    eng_cold = Engine(cfg, params, mesh, ecfg_p)
    eng_cold.warmup()

    def prefix_trace():
        reqs = []
        for i in range(n_requests):
            tail = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(910 + i), (1 + i % 8,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"p{i}", template + tail,
                                max_tokens=8, sampling=sp))
        return reqs

    # PAIRED measurement: the two sides run back-to-back inside each
    # round and the ratio is taken PER ROUND, then the median of the
    # round ratios is reported. Best-of-N per side (the old spelling)
    # let host drift land asymmetrically across the two best picks —
    # prefix_ttft_speedup wandered 1.638 → 1.896 → 1.315 over PRs
    # 7/8/10 on an unchanged admission path (pure measurement jitter);
    # .scratch/flightrec_ab.py's paired medians sat at 0.977–1.031 on
    # the same host. Same fix as the flight-recorder A/B below.
    best_pref = {}
    ptoks = {}
    pref_ratios = []
    pref_sides = (("hit", eng_pref), ("cold", eng_cold))
    for rnd in range(reps + 3):
        round_ttft = {}
        for name, eng in _ab_order(rnd, pref_sides):
            toks, s = run(eng, prefix_trace(), pipeline_depth=2,
                          max_admit_batch=1)
            ptoks.setdefault(name, toks)
            assert ptoks[name] == toks, f"prefix {name} rerun drift"
            round_ttft[name] = s["ttft_mean_ms"]
            if name not in best_pref or s["ttft_mean_ms"] < \
                    best_pref[name]["ttft_mean_ms"]:
                best_pref[name] = s
        pref_ratios.append(round_ttft["cold"]
                           / max(round_ttft["hit"], 1e-9))
    # bit-parity holds when cold prefill runs the materialised-scores
    # attention (prefill_extend's expression — the CPU mesh and any
    # xla attn_impl config); under flash prefill the two differ at the
    # reduction-order ulp level, so drift is REPORTED, not asserted
    # (docs/DESIGN.md "Serving round 6" known limits)
    pref_drift = sum(1 for k in ptoks["hit"]
                     if ptoks["hit"][k] != ptoks["cold"][k])
    if not on_tpu or cfg.attn_impl == "xla":
        assert pref_drift == 0, "prefix-hit token drift"
    hit_rate = best_pref["hit"]["prefix_hits"] / max(
        best_pref["hit"]["prefix_hits"]
        + best_pref["hit"]["prefix_misses"], 1)
    prefix_ab = {
        "split": tlen,
        "cold_bucket": eng_cold.bucket_for(tlen + 1),
        "hit_ttft_mean_ms": round(best_pref["hit"]["ttft_mean_ms"], 2),
        "cold_ttft_mean_ms": round(best_pref["cold"]["ttft_mean_ms"], 2),
        "ttft_speedup": round(_median(pref_ratios), 3),
        "hit_rate": round(hit_rate, 3),
        "token_drift": pref_drift,
    }
    eng_pref.close()
    eng_cold.close()

    # KV-cache capacity A/B #3 — paged cache: a global page pool +
    # per-slot block tables vs the contiguous one-stripe-per-slot
    # layout, on a MIXED-length trace (short and long prompts, varied
    # budgets — the workload where contiguous slots strand the most
    # HBM). The headline is cache bytes PINNED per active token,
    # time-averaged over the drive loop: the contiguous side pins a
    # full max_seq_len stripe per busy slot no matter how small the
    # request; the paged side pins only each request's pages. Streams
    # must be BIT-identical (paging is a layout play, not a numerics
    # play), so the paged side joins the capacity A/B's own parity
    # assert; steady decode rides along and must sit inside the host
    # noise band.
    page_sz = 8
    eng_paged = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, decode_chunk=8, page_size=page_sz))
    eng_paged.warmup()

    def mixed_trace():
        reqs = []
        for i in range(n_requests):
            # half the prompts short (1..6), half long (half..full
            # bucket), budgets varied small — the fragmentation mix
            if i % 2:
                p_len = 1 + (5 * i + 1) % 6
            else:
                p_len = ecfg.max_prompt_len // 2 + (7 * i) % (
                    ecfg.max_prompt_len // 2) + 1
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(500 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"m{i}", prompt,
                                max_tokens=1 + i % 6, sampling=sp))
        return reqs

    def run_tracked(eng, reqs, **kw):
        """run() with a per-tick occupancy probe: time-summed pinned
        cache bytes and active-request token footprints (the bytes-
        per-active-token numerator/denominator), host-side reads
        only."""
        sched = Scheduler(eng, **kw)
        for r in reqs:
            sched.submit(r)
        stripe = eng.cache_bytes() / eng.slots
        page_bytes = (eng.cache_bytes() / eng._num_pages
                      if eng.paged else 0.0)
        pinned_sum = tokens_sum = 0.0
        steps = 0
        while not sched.idle():
            sched.step()
            steps += 1
            if steps > 100_000:
                raise RuntimeError("paged A/B drive loop stuck")
            act_tokens = sum(
                len(a.request.prompt) + a.request.max_tokens
                for a in sched.active.values())
            if not act_tokens:
                continue
            if eng.paged:
                pinned = eng.page_allocator.pages_in_use * page_bytes
            else:
                pinned = len(sched.active) * stripe
            pinned_sum += pinned
            tokens_sum += act_tokens
        toks = {rid: c.tokens for rid, c in sched.completions.items()}
        return toks, sched.summary(), pinned_sum / max(tokens_sum, 1.0)

    best_pg = {}
    pg_toks = {}
    bpt = {}
    pg_ratios = []
    pg_sides = (("paged", eng_paged), ("contig", engine))
    for rnd in range(reps + 3):
        round_dec = {}
        for name, eng in _ab_order(rnd, pg_sides):
            toks, s, bytes_per_tok = run_tracked(
                eng, mixed_trace(), pipeline_depth=2)
            pg_toks.setdefault(name, toks)
            assert pg_toks[name] == toks, f"paged ab {name} rerun drift"
            bpt[name] = bytes_per_tok  # deterministic per side
            round_dec[name] = s.get("decode_tokens_per_sec", 0.0)
            if name not in best_pg or s.get(
                    "decode_tokens_per_sec", 0.0) > best_pg[name].get(
                    "decode_tokens_per_sec", 0.0):
                best_pg[name] = s
        pg_ratios.append(round_dec["paged"]
                         / max(round_dec["contig"], 1e-9))
    # paged == contiguous BIT-parity is engineered on the XLA path
    # (gathered bytes + verbatim score expressions); on chip BOTH
    # sides take the Pallas kernel path with DIFFERENT split-K block
    # granularities (one page vs _fit_block_k of the horizon), so the
    # online-softmax merge order differs at the ulp level and drift is
    # REPORTED, not asserted — the prefix A/B's flash caveat again
    pg_drift = sum(1 for k in pg_toks["paged"]
                   if pg_toks["paged"][k] != pg_toks["contig"][k])
    if not on_tpu:
        assert pg_drift == 0, "paged token drift"
    paged_ab = {
        "page_size": page_sz,
        "num_pages": eng_paged._num_pages,
        "contig_bytes_per_active_token": round(bpt["contig"], 1),
        "paged_bytes_per_active_token": round(bpt["paged"], 1),
        # the fragmentation-free capacity headline: how many MORE
        # active tokens the same HBM holds under paging on this mix
        "effective_capacity_gain": round(
            bpt["contig"] / max(bpt["paged"], 1e-9), 3),
        "contig_decode_tokens_per_sec": round(
            best_pg["contig"].get("decode_tokens_per_sec", 0.0), 1),
        "paged_decode_tokens_per_sec": round(
            best_pg["paged"].get("decode_tokens_per_sec", 0.0), 1),
        # paired per-round median, like every other ratio here
        "decode_ratio": round(_median(pg_ratios), 3),
        "page_fragmentation": round(
            best_pg["paged"].get("page_fragmentation", 0.0), 3),
        "token_drift": pg_drift,
    }
    eng_paged.close()

    # Chunked-prefill A/B — one long prompt admitted alongside a wave
    # of short ones (all at t=0, long first): monolithic admission
    # makes every short stream's TTFT wait out the long prefill
    # forward; chunked admission interleaves the long prompt's chunk
    # forwards with the shorts' decode waves. The observable is the
    # SHORT requests' mean TTFT vs a shorts-only baseline — paired
    # per-round ratios, median reported; the chunked side's inflation
    # must sit inside the host noise band. Streams bit-identical
    # between mono and chunked (prefill_extend parity — CPU mesh).
    mpl_c = min(4 * ecfg.max_prompt_len, cfg.seq_len // 2)
    chunk_c = ecfg.max_prompt_len
    ecfg_ck = dataclasses.replace(
        ecfg, decode_chunk=8, max_prompt_len=mpl_c,
        max_seq_len=mpl_c + 32)
    eng_mono = Engine(cfg, params, mesh, ecfg_ck).warmup()
    eng_chunk = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg_ck, prefill_chunk=chunk_c)).warmup()
    # one admission wave of shorts (slots - 1 of them, so none waits
    # on slot turnover), serial k=1 admissions on both sides: the
    # shorts' TTFT then isolates exactly the queue-behind-the-long-
    # prefill effect the interleave removes, not the k-ladder or slot
    # recycling
    n_short = ecfg.slots - 1

    def chunk_trace(with_long):
        reqs = []
        if with_long:
            long_p = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(600), (mpl_c,), 0, cfg.vocab_size)]
            reqs.append(Request("long", long_p, max_tokens=8,
                                sampling=SamplingParams()))
        for i in range(n_short):
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(610 + i), (1 + i % 8,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"c{i}", prompt, max_tokens=8,
                                sampling=sp))
        return reqs

    def short_ttft(engine, with_long):
        sched = Scheduler(engine, pipeline_depth=2, max_admit_batch=1)
        for r in chunk_trace(with_long):
            sched.submit(r)
        sched.run_until_idle()
        toks = {rid: c.tokens for rid, c in sched.completions.items()}
        ttfts = [c.ttft for rid, c in sched.completions.items()
                 if rid != "long" and c.ttft is not None]
        return toks, 1e3 * sum(ttfts) / max(len(ttfts), 1)

    ck_toks = {}
    infl = {"mono": [], "chunked": []}
    ck_best = {}
    ck_sides = (("mono", eng_mono), ("chunked", eng_chunk))
    for rnd in range(reps + 3):
        _, base_ms = short_ttft(eng_mono, with_long=False)
        for name, eng in _ab_order(rnd, ck_sides):
            toks, ms = short_ttft(eng, with_long=True)
            ck_toks.setdefault(name, toks)
            assert ck_toks[name] == toks, f"chunked {name} rerun drift"
            infl[name].append(ms / max(base_ms, 1e-9))
            ck_best[name] = min(ck_best.get(name, ms), ms)
    # chunked == monolithic BIT-parity holds under materialised-scores
    # cold prefill (the prefill_extend contract — every off-TPU
    # config); under flash cold prefill the two differ at the
    # reduction-order ulp level, so drift is REPORTED, not asserted
    # (the prefix A/B's caveat, inherited)
    ck_drift = sum(1 for k in ck_toks["mono"]
                   if ck_toks["mono"][k] != ck_toks["chunked"][k])
    if not on_tpu or cfg.attn_impl == "xla":
        assert ck_drift == 0, "chunked token drift"
    chunked_ab = {
        "long_prompt": mpl_c,
        "prefill_chunk": chunk_c,
        "short_ttft_mono_ms": round(ck_best["mono"], 2),
        "short_ttft_chunked_ms": round(ck_best["chunked"], 2),
        # short-stream TTFT inflation vs the shorts-only baseline
        # (paired per-round, median): the stall the interleave removes
        "ttft_inflation_mono": round(_median(infl["mono"]), 3),
        "ttft_inflation_chunked": round(_median(infl["chunked"]), 3),
        "token_drift": ck_drift,
    }
    eng_mono.close()
    eng_chunk.close()

    # Speculative-decoding A/B — draft-k-verify inside the compiled
    # chunk loop (gpt.decode_steps_spec), payoff-gated by the
    # scheduler's acceptance EWMA. Two traces, interleaved best-of-reps
    # against a plain engine (value-fetch sync throughout — run() only
    # counts fetched tokens): a REPETITIVE greedy trace (random-init
    # greedy decode collapses into short attractor cycles the n-gram
    # drafter replays — the high-acceptance regime) and an ADVERSARIAL
    # high-temperature trace (near-uniform tokens, drafts almost never
    # land — the gate must close and hold the plain path's numbers).
    # Streams must be bit-identical on BOTH traces (verification is
    # token-matching against the target's own draws), so the spec
    # sides join the sweep-wide drift assert below via the extra
    # main-trace side.
    eng_spec_main = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, decode_chunk=8, spec_k=3))
    eng_spec_main.warmup()
    measure_ab([("spec8", eng_spec_main, dict(pipeline_depth=2))])
    eng_spec_main.close()
    mpl_s = 16
    msl_s, mt_s, n_spec = ((96, 64, 6) if not on_tpu
                           else (192, 96, 16))
    ecfg_s = dataclasses.replace(
        ecfg, max_prompt_len=mpl_s, max_seq_len=msl_s, decode_chunk=4)
    eng_sp = Engine(cfg, params, mesh,
                    dataclasses.replace(ecfg_s, spec_k=3)).warmup()
    eng_pl = Engine(cfg, params, mesh, ecfg_s).warmup()

    def spec_trace(adversarial):
        reqs = []
        for i in range(n_spec):
            p_len = 1 + (11 * i + 5) % mpl_s
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(700 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=1.5, seed=i)
                  if adversarial else SamplingParams())
            reqs.append(Request(f"s{i}", prompt, max_tokens=mt_s,
                                sampling=sp))
        return reqs

    best_s = {}
    stoks = {}
    for _ in range(reps + 2):
        for tr_name, adv in (("high", False), ("adv", True)):
            for side, eng in (("spec", eng_sp), ("plain", eng_pl)):
                key = f"{tr_name}_{side}"
                toks, s = run(eng, spec_trace(adv), pipeline_depth=2)
                stoks.setdefault(key, toks)
                assert stoks[key] == toks, f"spec ab {key} rerun drift"
                if key not in best_s or s.get(
                        "decode_tokens_per_sec", 0.0) > best_s[key].get(
                        "decode_tokens_per_sec", 0.0):
                    best_s[key] = s
    # spec == plain bit-parity holds when BOTH step variants read the
    # cache through the same expressions — every off-TPU config. On
    # chip the plain path's split-K kernel read and the verify
    # forward's materialised read differ at the ulp level (the
    # prefix_ab flash caveat's sibling, docs/DESIGN.md "Serving round
    # 7"), so drift there is REPORTED, not asserted
    spec_drift = sum(
        1 for tr in ("high", "adv")
        for rid in stoks[f"{tr}_spec"]
        if stoks[f"{tr}_spec"][rid] != stoks[f"{tr}_plain"][rid])
    if not on_tpu:
        assert spec_drift == 0, "spec-vs-plain token drift"
    dec = lambda k: best_s[k].get("decode_tokens_per_sec", 0.0)
    spec_ab = {
        "spec_k": 3,
        "high_spec_decode_tokens_per_sec": round(dec("high_spec"), 1),
        "high_plain_decode_tokens_per_sec": round(dec("high_plain"), 1),
        "high_speedup": round(dec("high_spec")
                              / max(dec("high_plain"), 1e-9), 3),
        "high_accept_rate": round(
            best_s["high_spec"].get("spec_accept_rate", 0.0), 3),
        "adversarial_ratio": round(dec("adv_spec")
                                   / max(dec("adv_plain"), 1e-9), 3),
        "adversarial_accept_rate": round(
            best_s["adv_spec"].get("spec_accept_rate", 0.0), 3),
        "adversarial_gate_state": best_s["adv_spec"].get(
            "spec_gate_state", -1.0),
        "token_drift": spec_drift,
    }
    eng_sp.close()
    eng_pl.close()

    # Flight-recorder A/B — the always-on black box must be free:
    # interleaved best-of-reps on the warm chunk=8 engine, recorder on
    # vs off (same trace, same scheduler knobs). The recorder is pure
    # O(1) host tuple appends, so the ratio must sit inside the host
    # noise band; events_per_sec and the atomic bundle-write latency
    # ride into the trajectory line (the operator's budget numbers).
    from apex_tpu.telemetry.flightrec import FlightRecorder

    import shutil
    import tempfile

    # PAIRED per-round ratios, median reported — the same fix as the
    # prefix A/B above: independent best-of-N per side let host drift
    # land asymmetrically (PR 10's trajectory recorded 1.334, outside
    # the 0.74–1.23 host band, while .scratch/flightrec_ab.py's paired
    # medians sat at 0.977–1.031 on the same host and the recorder's
    # unit cost is ~0.9 us/event — the bench was measuring noise)
    rec_events_total = 0
    best_fr = {}
    fr_ratios = []
    for rnd in range(reps + 3):
        round_tps = {}
        for name in _ab_order(rnd, ("flightrec", "plain")):
            fr = FlightRecorder() if name == "flightrec" else None
            sched = Scheduler(engine, pipeline_depth=2, recorder=fr)
            for r in trace(100, n_requests):
                sched.submit(r)
            t0 = time.perf_counter()
            sched.run_until_idle()
            wall = time.perf_counter() - t0
            toks = {rid: c.tokens for rid, c in
                    sched.completions.items()}
            assert toks == tokens_by_cfg["chunk8"], \
                f"flightrec ab {name} token drift"
            s = sched.summary()
            s["_wall"] = wall
            round_tps[name] = s["tokens_per_sec"]
            if fr is not None:
                rec_events_total = fr.summary()["events_total"]
                s["_events_per_sec"] = rec_events_total / max(wall,
                                                              1e-9)
                last_fr_sched = sched
            if name not in best_fr or s["tokens_per_sec"] > \
                    best_fr[name]["tokens_per_sec"]:
                best_fr[name] = s
        fr_ratios.append(round_tps["flightrec"]
                         / max(round_tps["plain"], 1e-9))
    # bundle-write latency: median-of-3 atomic dumps of the freshly
    # soaked scheduler state (events + requests + config + registry)
    tmp = tempfile.mkdtemp(prefix="apex_bundle_ab_")
    dump_walls = []
    for i in range(3):
        t0 = time.perf_counter()
        last_fr_sched.dump_bundle("bench", bundle_dir=tmp)
        dump_walls.append(time.perf_counter() - t0)
    shutil.rmtree(tmp, ignore_errors=True)
    flightrec_ab = {
        "recorder_tokens_per_sec": round(
            best_fr["flightrec"]["tokens_per_sec"], 1),
        "plain_tokens_per_sec": round(
            best_fr["plain"]["tokens_per_sec"], 1),
        # median of the interleaved per-round paired ratios (see above)
        "overhead_ratio": round(_median(fr_ratios), 3),
        "events_total": rec_events_total,
        "events_per_sec": round(
            best_fr["flightrec"]["_events_per_sec"], 1),
        "bundle_write_ms": round(
            1e3 * sorted(dump_walls)[len(dump_walls) // 2], 3),
        "token_drift": 0,
    }

    # SLO-observatory A/B — full ingestion on (four quantile sketches
    # fed per token/admission/completion + a live burn-rate machine)
    # vs off, same trace, same knobs, paired per-round ratios like the
    # flight-recorder A/B above. Sketch adds are O(1) dict bumps and
    # gauge refresh is eval-cadence, so the ratio must sit inside the
    # host noise band. The slo side's sketch-backed p99 TTFT rides
    # into the trajectory next to tok/s.
    from apex_tpu.telemetry.slo import SLOConfig, parse_objective

    slo_cfg_ab = SLOConfig(
        objectives=(parse_objective("p99:ttft:0.2"),
                    parse_objective("p95:e2e:1.0")),
        eval_every_s=0.02, snapshot_every_s=0.1)
    best_slo = {}
    slo_ratios = []
    slo_summary = None
    for rnd in range(reps + 3):
        round_tps = {}
        for name in _ab_order(rnd, ("slo", "plain")):
            sched = Scheduler(
                engine, pipeline_depth=2,
                slo=slo_cfg_ab if name == "slo" else None)
            for r in trace(100, n_requests):
                sched.submit(r)
            sched.run_until_idle()
            toks = {rid: c.tokens for rid, c in
                    sched.completions.items()}
            assert toks == tokens_by_cfg["chunk8"], \
                f"slo ab {name} token drift"
            s = sched.summary()
            round_tps[name] = s["tokens_per_sec"]
            if name == "slo":
                slo_summary = s
            if name not in best_slo or s["tokens_per_sec"] > \
                    best_slo[name]["tokens_per_sec"]:
                best_slo[name] = s
        slo_ratios.append(round_tps["slo"]
                          / max(round_tps["plain"], 1e-9))
    slo_ab = {
        "slo_tokens_per_sec": round(
            best_slo["slo"]["tokens_per_sec"], 1),
        "plain_tokens_per_sec": round(
            best_slo["plain"]["tokens_per_sec"], 1),
        # median of the interleaved per-round paired ratios
        "overhead_ratio": round(_median(slo_ratios), 3),
        "sketch_ttft_p50_ms": round(
            slo_summary.get("slo_ttft_p50_ms", 0.0), 3),
        "sketch_ttft_p99_ms": round(
            slo_summary.get("slo_ttft_p99_ms", 0.0), 3),
        "sketch_token_latency_p99_ms": round(
            slo_summary.get("slo_token_latency_p99_ms", 0.0), 3),
        "budget_remaining": round(
            slo_summary.get("slo_budget_remaining", 1.0), 6),
        "state": slo_summary.get("slo_state", 0.0),
        "token_drift": 0,
    }

    # Self-tuning A/B — the serving.tuner control plane vs every FIXED
    # operating point on a SHIFTING burst trace: phase A is
    # decode-heavy (few requests, long budgets — big chunks amortize
    # dispatch), then once half of A has drained phase B floods short
    # admission-heavy requests (small budgets — wide chunks burn pad
    # columns at finish boundaries). No single fixed (chunk, depth)
    # corner is right for both phases; the controller re-converges
    # mid-run. Ratio reported vs the BEST fixed corner per paired
    # round (median), streams bit-identical across every side (the
    # chunk/pipeline invariance oracles extended over controller
    # switching).
    from apex_tpu.serving.tuner import TunerConfig

    # longer horizon than the headline shape: the decode-heavy phase
    # needs enough chunks at EVERY rung for the controller's measure +
    # probe windows to actually run (the first cut of this A/B ended
    # before the first probe window opened — probes=0 is a no-op
    # controller, not a measurement)
    ecfg_t = dataclasses.replace(ecfg, max_seq_len=48)
    eng_tune = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg_t, decode_chunk=8, decode_chunks=(2, 8))).warmup()
    eng_c2 = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg_t, decode_chunk=2)).warmup()
    eng_c8 = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg_t, decode_chunk=8)).warmup()
    mt_long = min(24, ecfg_t.max_seq_len - ecfg_t.max_prompt_len)

    def shifting_trace():
        a, b = [], []
        for i in range(3 * ecfg.slots):
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(800 + i),
                (1 + (7 * i) % ecfg.max_prompt_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            a.append(Request(f"ta{i}", prompt, max_tokens=mt_long,
                             sampling=sp))
        for i in range(6 * ecfg.slots):
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(850 + i), (1 + i % 4,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40,
                                 seed=100 + i)
                  if i % 2 else SamplingParams())
            b.append(Request(f"tb{i}", prompt, max_tokens=2 + i % 3,
                             sampling=sp))
        return a, b

    def run_shifting(engine, **sched_kw):
        sched = Scheduler(engine, **sched_kw)
        a, b = shifting_trace()
        for r in a:
            sched.submit(r)
        steps = 0
        while sum(1 for r in a
                  if r.request_id in sched.completions) < len(a) // 2:
            sched.step()
            steps += 1
            if steps > 100_000:
                raise RuntimeError("tuner A/B phase A stuck")
        for r in b:  # the shift: short-burst admission pressure
            sched.submit(r)
        sched.run_until_idle()
        return ({rid: c.tokens for rid, c in
                 sched.completions.items()}, sched.summary())

    tuner_cfg = TunerConfig(decode_chunk=(2, 8), pipeline_depth=(1, 2),
                            probe_every=3, probe_chunks=1,
                            min_measure_chunks=2)
    fixed_sides = (
        ("fixed_c2_d1", eng_c2, dict(pipeline_depth=1)),
        ("fixed_c2_d2", eng_c2, dict(pipeline_depth=2)),
        ("fixed_c8_d1", eng_c8, dict(pipeline_depth=1)),
        ("fixed_c8_d2", eng_c8, dict(pipeline_depth=2)),
    )
    tn_toks = {}
    tn_best = {}
    tn_ratios = []
    tn_base_ratios = []
    auto_summary = None
    for rnd in range(reps + 2):
        round_tps = {}
        sides = fixed_sides + (("autotuned", eng_tune,
                                dict(pipeline_depth=2,
                                     tuner=tuner_cfg)),)
        for name, eng, kw in _ab_order(rnd, sides):
            toks, s = run_shifting(eng, **kw)
            tn_toks.setdefault(name, toks)
            assert tn_toks[name] == toks, f"tuner ab {name} rerun drift"
            round_tps[name] = s["tokens_per_sec"]
            if name == "autotuned":
                auto_summary = s
            if name not in tn_best or s["tokens_per_sec"] > \
                    tn_best[name]["tokens_per_sec"]:
                tn_best[name] = s
        best_fixed = max(round_tps[n] for n, _, _ in fixed_sides)
        tn_ratios.append(round_tps["autotuned"] / max(best_fixed, 1e-9))
        # vs the autotuned run's own BASE corner (chunk 8, depth 2) —
        # the config you would have shipped without a controller; the
        # best-fixed ratio above is oracle regret (nobody knows the
        # best corner a priori — that is the controller's whole job)
        tn_base_ratios.append(
            round_tps["autotuned"] / max(round_tps["fixed_c8_d2"],
                                         1e-9))
    tn_drift = [name for name in tn_toks
                if tn_toks[name] != tn_toks["autotuned"]]
    assert not tn_drift, f"tuner A/B token drift in {tn_drift}"
    assert auto_summary["tuner_probes"] > 0, \
        "autotuned side never probed — the A/B measured a no-op"
    best_fixed_name = max((n for n, _, _ in fixed_sides),
                          key=lambda n: tn_best[n]["tokens_per_sec"])
    tuner_ab = {
        "ladders": {"decode_chunk": [2, 8], "pipeline_depth": [1, 2]},
        "autotuned_tokens_per_sec": round(
            tn_best["autotuned"]["tokens_per_sec"], 1),
        "best_fixed": best_fixed_name,
        "best_fixed_tokens_per_sec": round(
            tn_best[best_fixed_name]["tokens_per_sec"], 1),
        # paired per-round medians: oracle regret vs the round's best
        # fixed corner, and the shipped-default comparison vs base
        "ratio_vs_best_fixed": round(_median(tn_ratios), 3),
        "ratio_vs_base": round(_median(tn_base_ratios), 3),
        "probes": auto_summary.get("tuner_probes", 0.0),
        "switches": auto_summary.get("tuner_switches", 0.0),
        "final_decode_chunk": auto_summary.get("tuner_decode_chunk"),
        "final_pipeline_depth": auto_summary.get(
            "tuner_pipeline_depth"),
        "token_drift": 0,
    }
    eng_tune.close()
    eng_c2.close()
    eng_c8.close()

    # -- multi-tenant serving A/B (tenancy + batched multi-LoRA) ---------
    # (a) adapter-pool overhead: the SAME standard burst on an engine
    # whose every dense seam carries the gather+rank-r delta, all rows
    # riding the pinned zero adapter — paired per-round ratio vs the
    # plain chunk=8 engine, and the zero-adapter streams join the
    # sweep-wide drift assert (base traffic must be bit-identical);
    # (b) a contended multi-tenant trace — three tenants at skewed
    # weights, two of them on registered LoRA adapters — measured
    # MID-FLOOD for the weighted fairness ratio (min/max per-tenant
    # tokens/weight; 1.0 = perfect WFQ convergence), with a
    # weighted-vs-unweighted rerun drift assert (scheduling order must
    # never change a stream's tokens) and a rate-limit shed count from
    # a throttled-tenant rerun.
    from apex_tpu.serving.tenancy import TenancyConfig, TenantThrottled

    eng_mt = Engine(cfg, params, mesh, dataclasses.replace(
        ecfg, decode_chunk=8, adapter_slots=3, adapter_rank=4,
        adapter_alpha=8.0))
    eng_mt.warmup()
    eng_mt.register_adapter(seed=71)
    eng_mt.register_adapter(seed=72)
    ovr = []
    for rnd in range(reps):
        tps = {}
        for name, eng_, kw in _ab_order(rnd, (
                ("chunk8", engine, dict(pipeline_depth=2)),
                ("tenant_base", eng_mt, dict(pipeline_depth=2)))):
            toks, s = run(eng_, trace(100, n_requests), **kw)
            tokens_by_cfg.setdefault(name, toks)
            assert tokens_by_cfg[name] == toks, f"{name} rerun drift"
            tps[name] = s["tokens_per_sec"]
        ovr.append(tps["tenant_base"] / max(tps["chunk8"], 1e-9))

    def tenant_trace(seed0, mult=12):
        # staggered budgets: uniform ones make all slots release in
        # lockstep, so service moves in whole-tenant quanta and the
        # fairness window reads noise — varied budgets stagger the
        # releases and WFQ picks happen per slot
        reqs = []
        lanes = (("ta", 1), ("tb", 2), ("tc", 0))
        for i in range(mult * n_requests):
            t, adapter = lanes[i % 3]
            p_len = 1 + (7 * i + 3) % ecfg.max_prompt_len
            prompt = [int(x) for x in jax.random.randint(
                jax.random.PRNGKey(seed0 + i), (p_len,), 0,
                cfg.vocab_size)]
            sp = (SamplingParams(temperature=0.9, top_k=40, seed=i)
                  if i % 2 else SamplingParams())
            reqs.append(Request(f"{t}-{i}", prompt,
                                max_tokens=2 + (5 * i) % max_tokens,
                                sampling=sp, tenant=t,
                                adapter=adapter))
        return reqs

    def run_tenants(tenancy, depth=2, admit_cap=None):
        sched = Scheduler(eng_mt, tenancy=tenancy,
                          pipeline_depth=depth,
                          max_admit_batch=admit_cap,
                          max_queue=16 * 3 * n_requests)
        reqs = tenant_trace(700)
        for r in reqs:
            sched.submit(r)
        # steady-state fairness window: per-tenant served-token DELTAS
        # over the [1/4, 1/2] completion window, normalized by weight
        # — the start cut drops the round-robin first wave (deficits
        # start equal), the end cut keeps every tenant backlogged (the
        # favoured tenant drains its backlog first, and a later window
        # would read its empty-queue tail as unfairness)
        snap = {}
        total = len(reqs)
        marks = (total // 4, total // 2)
        while len(sched.completions) < total:
            sched.step()
            done = len(sched.completions)
            for mark in marks:
                if mark not in snap and done >= mark:
                    snap[mark] = {t: row["tokens"] for t, row in
                                  sched.tenant_summary().items()}
        sched.run_until_idle()
        mid = None
        if len(snap) == 2:
            s1, s2 = (snap[m] for m in marks)
            book = sched.tenants
            mid = {t: (s2[t] - s1.get(t, 0.0)) / book.weight(t)
                   for t in s2}
        return ({rid: c.tokens for rid, c in
                 sched.completions.items()}, mid, sched.summary())

    weights = {"ta": 3.0, "tb": 2.0, "tc": 1.0}
    # the fairness side runs the SERIAL loop with one admission per
    # tick: WFQ picks then see deficits fresh to the last fetched
    # chunk (a deep pipeline's stale-by-a-wave deficits blur the
    # shares at smoke scale); streams are depth/batch-invariant, so
    # the drift assert against the pipelined unweighted run still
    # pins WFQ-order token invariance
    toks_w, mid_w, sum_w = run_tenants(
        TenancyConfig(weights=weights, aging_per_s=0.1), depth=1,
        admit_cap=1)
    toks_u, _, _ = run_tenants(None)
    assert toks_w == toks_u, \
        "tenant A/B token drift (WFQ order changed a stream)"
    fairness = (min(mid_w.values()) / max(max(mid_w.values()), 1e-9)
                if mid_w else 0.0)
    # rate-limited rerun: tenant tc capped hard — its overflow 429s
    # while ta/tb streams stay bit-identical to the uncapped run
    sched_rl = Scheduler(
        eng_mt, pipeline_depth=2, max_queue=16 * 3 * n_requests,
        tenancy=TenancyConfig(weights=weights,
                              rates={"tc": float(max_tokens)},
                              burst_s=1.0))
    throttled = 0
    for r in tenant_trace(700):
        try:
            sched_rl.submit(r)
        except TenantThrottled:
            throttled += 1
    sched_rl.run_until_idle()
    for rid, c in sched_rl.completions.items():
        if not rid.startswith("tc"):
            assert c.tokens == toks_w[rid], \
                f"throttled-tenant run changed {rid}'s stream"
    assert throttled > 0, "rate-limit rerun never throttled"
    tenant_ab = {
        "tenants": len(weights),
        "weights": weights,
        "adapters": int(eng_mt.adapters_registered),
        "adapter_overhead_ratio": round(_median(ovr), 3),
        "fairness_min_max_ratio": round(fairness, 3),
        "midpoint_tokens_per_weight": {
            t: round(v, 1) for t, v in sorted(mid_w.items())},
        "throttled_429s": throttled,
        "tenant_throttled_metric": sched_rl.summary().get(
            "tenant_throttled", 0.0),
        "token_drift": 0,
    }
    eng_mt.close()

    # the loop/admission knobs must not change a single emitted token —
    # sweep-wide: every chunk setting, serial vs pipelined, flat vs
    # bucketed/batched admission, spec on vs off (the int8 side is
    # numerics-excluded above; on chip the spec side joins it — the
    # plain kernel read vs the verify forward's materialised read
    # differ at the ulp level there, see the spec A/B note)
    excluded = {"kv_int8"} | ({"spec8"} if on_tpu else set())
    base = tokens_by_cfg["chunk1"]
    drift = [k for k, v in tokens_by_cfg.items()
             if k not in excluded and v != base]
    assert not drift, f"serve sweep token drift in {drift}"
    api_line = None
    if api:
        api_line = _api_wire_load(engine, trace(100, n_requests), base,
                                  cfg.vocab_size)
    if telemetry_out:
        # snapshot from a SEPARATE instrumented replay of the headline
        # (chunk=8, pipelined) trace on the already-warm engine — the
        # measured sweep above stays uninstrumented, so the trajectory
        # metric is comparable whether or not this flag is passed
        registry = Registry()
        sched = Scheduler(engine, registry=registry, pipeline_depth=2)
        for r in trace(100, n_requests):
            sched.submit(r)
        sched.run_until_idle()
    line = {
        "metric": "gpt2_355m_serve_tokens_per_sec_per_chip" if on_tpu
        else "gpt_serve_smoke_cpu_tokens_per_sec",
        "value": head["tokens_per_sec"],
        "unit": "tokens/s",
        "requests": n_requests,
        "slots": ecfg.slots,
        "decode_chunk": 8,
        "pipeline_depth": 2,
        # TTFT (admission/prefill) vs steady-decode split at the
        # headline chunk, then the sweeps for trajectory tracking
        "ttft_mean_ms": head["ttft_mean_ms"],
        "ttft_p99_ms": head["ttft_p99_ms"],
        "decode_tokens_per_sec": head["decode_tokens_per_sec"],
        "token_latency_mean_ms": head["token_latency_mean_ms"],
        "cache_bytes_per_slot": engine.cache_bytes() // ecfg.slots,
        "chunk_sweep": sweep,
        "pipeline_ab": pipeline_ab,
        "bucket_ab": bucket_ab,
        "kv_cache_ab": kv_ab,
        "prefix_ab": prefix_ab,
        "paged_ab": paged_ab,
        "chunked_ab": chunked_ab,
        "spec_ab": spec_ab,
        "flightrec_ab": flightrec_ab,
        "slo_ab": slo_ab,
        "tuner_ab": tuner_ab,
        "tenant_ab": tenant_ab,
    }
    if not on_tpu:
        line["probe_ab_1l32h"] = line_probe
    if api_line is not None:
        line["api"] = api_line
    if telemetry_out == "-":
        line["telemetry"] = registry.to_dict()
    elif telemetry_out:
        with open(telemetry_out, "w") as f:
            json.dump(registry.to_dict(), f, indent=1, sort_keys=True)
        line["telemetry_out"] = telemetry_out
    # trajectory file: one compact line per serve-bench run, appended —
    # the BENCH_serve.json series tracks the serving headline (tok/s,
    # TTFT, cache bytes/slot, prefix-hit economics) across PRs
    traj = {
        "pr": BENCH_PR,
        "label": BENCH_LABEL,
        "metric": line["metric"],
        "tokens_per_sec": line["value"],
        "decode_tokens_per_sec": line["decode_tokens_per_sec"],
        "ttft_mean_ms": line["ttft_mean_ms"],
        "cache_bytes_per_slot": line["cache_bytes_per_slot"],
        "kv_int8_bytes_ratio": kv_ab["bytes_ratio"],
        "prefix_hit_rate": prefix_ab["hit_rate"],
        "prefix_ttft_speedup": prefix_ab["ttft_speedup"],
        # paged-cache successor metrics: bytes pinned per active token
        # and the fragmentation-free capacity gain on the mixed trace;
        # chunked prefill's short-stream TTFT inflation (vs 1.0 = no
        # stall) next to the monolithic baseline's
        "cache_bytes_per_active_token": paged_ab[
            "paged_bytes_per_active_token"],
        "paged_capacity_gain": paged_ab["effective_capacity_gain"],
        "paged_decode_ratio": paged_ab["decode_ratio"],
        "chunked_ttft_inflation": chunked_ab["ttft_inflation_chunked"],
        "chunked_ttft_inflation_mono": chunked_ab[
            "ttft_inflation_mono"],
        "spec_accept_rate": spec_ab["high_accept_rate"],
        "spec_decode_tokens_per_sec": spec_ab[
            "high_spec_decode_tokens_per_sec"],
        "flightrec_overhead_ratio": flightrec_ab["overhead_ratio"],
        "events_per_sec": flightrec_ab["events_per_sec"],
        "bundle_write_ms": flightrec_ab["bundle_write_ms"],
        # SLO observatory: sketch-backed p99 TTFT next to tok/s (the
        # headline LatencyStats p99 for cross-checking) + the paired
        # ingestion-overhead ratio (1.0 = free)
        "ttft_p99_ms": line["ttft_p99_ms"],
        "slo_ttft_p99_ms": slo_ab["sketch_ttft_p99_ms"],
        "slo_overhead_ratio": slo_ab["overhead_ratio"],
        # self-tuning: autotuned vs the best fixed corner on the
        # shifting burst trace (paired per-round median)
        "tuner_ab": tuner_ab["ratio_vs_best_fixed"],
        # multi-tenant serving: adapter-pool overhead on base traffic
        # (paired median, 1.0 = free) and mid-flood weighted fairness
        # (min/max per-tenant tokens/weight, 1.0 = perfect WFQ)
        "adapter_overhead_ratio": tenant_ab["adapter_overhead_ratio"],
        "tenant_fairness": tenant_ab["fairness_min_max_ratio"],
    }
    line["bench_out"] = _append_traj(traj)
    print(json.dumps(line))


def main():
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = gpt.GPTConfig(  # GPT-2 355M
            vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
            seq_len=1024, remat=True, ce_chunk=512,
            compute_dtype=jnp.bfloat16,
            # measured on v5e: Pallas flash (512x512 tiles, lane-packed
            # [b, s, hidden] layout — attn_layout="auto") beats both XLA
            # attention variants once the whole step is jitted; XLA-fused
            # LN beats the opaque Pallas LN call inside the layer scan;
            # pinning qkv/fc1 projections AND the flash kernel's (out,
            # lse) residuals (backward never re-runs the fwd attention
            # kernel) at the MXU-aligned b=16 beats every larger-batch
            # fuller-remat combination tried
            attn_impl="flash", ln_impl="xla", remat_policy="qkv_fc1_attn",
        )
        batch, steps = 16, 15
    else:  # CPU smoke fallback so the harness always gets a line
        cfg = gpt.GPTConfig(
            vocab_size=1024, hidden_size=256, num_layers=4, num_heads=8,
            seq_len=256, remat=True, compute_dtype=jnp.bfloat16,
        )
        batch, steps = 4, 3

    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])
    # tree-layout Adam: moments mirror the (few, large, layer-stacked)
    # param leaves — no flat-packing copies, ~4 GB lower peak HBM
    init_fn, step_fn = training.make_train_step(
        cfg, mesh, fused_adam(1e-4, layout="tree"),
        ScalerConfig(enabled=False))
    state = init_fn(jax.random.PRNGKey(0))
    tok = jax.random.randint(
        jax.random.PRNGKey(1), (batch, cfg.seq_len), 0, cfg.vocab_size)
    tgt = jnp.roll(tok, -1, axis=1)

    # warmup / compile; the float() fetch is the sync barrier throughout —
    # through the remote-device tunnel, block_until_ready can return at
    # dispatch time, a value fetch cannot
    state, m = step_fn(state, tok, tgt)
    _ = float(m["loss"])

    best = float("inf")
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step_fn(state, tok, tgt)
        _ = float(m["loss"])
        best = min(best, time.perf_counter() - t0)

    tokens_per_sec = batch * cfg.seq_len * steps / best
    print(json.dumps({
        "metric": "gpt2_355m_train_tokens_per_sec_per_chip" if on_tpu
        else "gpt_smoke_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("train", "serve"), default="train",
                    help="train (default): whole-step training "
                    "throughput; serve: continuous-batching decode "
                    "throughput + TTFT/latency at a fixed request trace")
    ap.add_argument("--telemetry-out", metavar="PATH", default=None,
                    help="serve mode: dump the telemetry-registry "
                    "snapshot of the headline run — '-' embeds it in "
                    "the JSON line, anything else writes that file")
    ap.add_argument("--chaos", action="store_true",
                    help="serve mode: run the seeded fault-injection "
                    "smoke (one fault per engine seam) instead of the "
                    "throughput sweep — asserts recovery + zero token "
                    "drift for unaffected requests")
    ap.add_argument("--api", action="store_true",
                    help="serve mode: additionally drive the burst "
                    "trace through a live apex_tpu.serving.api HTTP "
                    "server (SSE streaming) — wire-level served tok/s "
                    "+ TTFT, with a zero-token-drift assert against "
                    "the in-process engine")
    ap.add_argument("--fleet", action="store_true",
                    help="serve mode: run the fleet failover A/B "
                    "(fleet-of-2 with a deterministic kill-one-"
                    "replica-mid-burst drill vs a clean single "
                    "replica) — asserts recovery + zero token drift "
                    "and appends a fleet-router BENCH_serve.json line")
    ap.add_argument("--crash", action="store_true",
                    help="serve mode: run the durable-journal A/B "
                    "(write-ahead request journal on vs off, paired "
                    "rounds) + an in-process crash-and-recover drill "
                    "— asserts the journal tax stays inside the noise "
                    "band, recovered streams are bit-identical, and "
                    "appends a durable-journal BENCH_serve.json line")
    ap.add_argument("--oversub", action="store_true",
                    help="serve mode: run the KV-oversubscription A/B "
                    "(idle-heavy trace over a host-swap engine vs the "
                    "same hard-capped page pool) — asserts >= 4x "
                    "resident conversations per chip + zero token "
                    "drift, prices swap-vs-recompute resume, and "
                    "appends an oversub BENCH_serve.json line")
    args = ap.parse_args()
    if args.mode == "serve":
        if args.chaos:
            chaos_smoke()
        elif args.fleet:
            fleet_smoke()
        elif args.oversub:
            oversub_smoke()
        elif args.crash:
            crash_smoke()
        else:
            serve(telemetry_out=args.telemetry_out, api=args.api)
    else:
        main()
