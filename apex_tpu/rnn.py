"""RNN cells — apex/RNN/{models,cells,RNNBackend}.py (U) (deprecated
upstream, kept for surface parity).

Fused LSTM/GRU cells: the reference fuses the gate math into single CUDA
kernels; on TPU the gate GEMMs are one fused [4h]/[3h] matmul and XLA
fuses the elementwise gate chain. Layers run under ``lax.scan`` (the
compiled analogue of the reference's Python time loop).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def lstm_cell(x, h, c, wi, wh, b=None):
    """One LSTM step: gates from one fused [.., 4h] GEMM pair.

    Gate order (i, f, g, o) — torch convention the reference follows.
    """
    z = x @ wi + h @ wh
    if b is not None:
        z = z + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new, c_new


def gru_cell(x, h, wi, wh, b=None):
    """One GRU step (torch gate order r, z, n)."""
    zi = x @ wi
    zh = h @ wh
    if b is not None:
        zi = zi + b
    ri, zi_g, ni = jnp.split(zi, 3, axis=-1)
    rh, zh_g, nh = jnp.split(zh, 3, axis=-1)
    r = jax.nn.sigmoid(ri + rh)
    z = jax.nn.sigmoid(zi_g + zh_g)
    n = jnp.tanh(ni + r * nh)
    return (1.0 - z) * n + z * h


@dataclasses.dataclass(frozen=True)
class LSTM:
    """Single-layer LSTM over [T, B, in] (apex ``RNN/models.py`` LSTM (U))."""

    input_size: int
    hidden_size: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    def init(self, key):
        k1, k2 = jax.random.split(key)
        bound = 1.0 / self.hidden_size ** 0.5
        p = {
            "wi": jax.random.uniform(
                k1, (self.input_size, 4 * self.hidden_size),
                self.param_dtype, -bound, bound),
            "wh": jax.random.uniform(
                k2, (self.hidden_size, 4 * self.hidden_size),
                self.param_dtype, -bound, bound),
        }
        if self.bias:
            p["b"] = jnp.zeros((4 * self.hidden_size,), self.param_dtype)
        return p

    def apply(self, params, xs, state: Optional[Tuple] = None):
        """xs [T, B, in] → (ys [T, B, h], (h, c))."""
        bsz = xs.shape[1]
        if state is None:
            h = jnp.zeros((bsz, self.hidden_size), xs.dtype)
            c = jnp.zeros((bsz, self.hidden_size), xs.dtype)
        else:
            h, c = state

        def step(carry, x):
            h, c = carry
            h, c = lstm_cell(x, h, c, params["wi"], params["wh"],
                             params.get("b"))
            return (h, c), h

        (h, c), ys = lax.scan(step, (h, c), xs)
        return ys, (h, c)
