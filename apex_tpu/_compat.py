"""JAX version compatibility shims.

The repo targets the modern ``jax.shard_map`` entry point (keyword-only,
``check_vma``); older runtimes ship it as
``jax.experimental.shard_map.shard_map`` (``check_rep``). Installing the
adapter at package import keeps every call site on the one modern
spelling instead of scattering try/except through models, tests, and
examples. No-op on runtimes that already expose ``jax.shard_map``.
"""

from __future__ import annotations

import jax

#: True when the legacy ``jax.experimental.shard_map`` adapter is in
#: place. Legacy ``check_rep`` inference is weaker than modern
#: ``check_vma`` (e.g. it cannot see replication through a
#: ``jax.grad``-of-psum), so callers that rely on the stronger
#: inference gate on this flag.
LEGACY_SHARD_MAP = False


def _install_shard_map() -> None:
    global LEGACY_SHARD_MAP
    if getattr(jax, "shard_map", None) is not None:
        return
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - no known runtime hits this
        return
    LEGACY_SHARD_MAP = True

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  **kwargs):
        kwargs.pop("axis_names", None)  # legacy maps over all mesh axes
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_vma, **kwargs)

    shard_map.__doc__ = _legacy.__doc__
    jax.shard_map = shard_map


def register_monitoring_listeners(on_event, on_duration):
    """Subscribe to the runtime's compile-event stream
    (``jax.monitoring``), returning an unregister callable — or ``None``
    on legacy runtimes without the module, in which case the caller
    falls back to polling its tracked functions' jit-cache sizes (the
    lowering/cache-miss counter the recompile sentinel keeps anyway).

    ``on_event(name, **kw)`` receives point events (persistent-cache
    hits/misses); ``on_duration(name, seconds, **kw)`` receives duration
    events — ``/jax/core/compile/backend_compile_duration`` is the one
    that matters: it fires whenever a new executable materialises
    (fresh XLA compile OR persistent-cache load) and never on an
    in-memory jit-cache hit.
    """
    try:
        from jax import monitoring
    except ImportError:  # pragma: no cover - legacy runtime
        return None
    # require BOTH registration APIs before touching either — a partial
    # register with no unregister handle would leak for process lifetime
    if not (hasattr(monitoring, "register_event_listener") and
            hasattr(monitoring, "register_event_duration_secs_listener")):
        return None  # pragma: no cover - legacy runtime
    # unregistration only exists as private helpers, living on the
    # implementation module (jax._src.monitoring — the public re-export
    # does NOT carry them on this runtime). Resolve them BEFORE
    # registering: a runtime where they are gone (they are private, no
    # stability guarantee) gets the clean cache-polling fallback instead
    # of listeners that Engine.close() can never release.
    impl = monitoring
    if not hasattr(impl, "_unregister_event_listener_by_callback"):
        try:
            from jax._src import monitoring as impl  # type: ignore
        except ImportError:  # pragma: no cover
            return None
    unreg_event = getattr(impl, "_unregister_event_listener_by_callback",
                          None)
    unreg_duration = getattr(
        impl, "_unregister_event_duration_listener_by_callback", None)
    if unreg_event is None or unreg_duration is None:
        return None  # pragma: no cover - future runtime

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)

    def unregister():
        for fn, cb in ((unreg_event, on_event),
                       (unreg_duration, on_duration)):
            try:
                fn(cb)
            except ValueError:  # already removed
                pass

    return unregister


def _install_axis_size() -> None:
    if getattr(jax.lax, "axis_size", None) is not None:
        return

    def axis_size(axis_name):
        # psum of a Python literal constant-folds to the (static) axis
        # size — the documented pre-axis_size spelling of the same query
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


_install_shard_map()
_install_axis_size()
