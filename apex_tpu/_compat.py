"""JAX version compatibility shims.

The repo targets the modern ``jax.shard_map`` entry point (keyword-only,
``check_vma``); older runtimes ship it as
``jax.experimental.shard_map.shard_map`` (``check_rep``). Installing the
adapter at package import keeps every call site on the one modern
spelling instead of scattering try/except through models, tests, and
examples. No-op on runtimes that already expose ``jax.shard_map``.
"""

from __future__ import annotations

import jax

#: True when the legacy ``jax.experimental.shard_map`` adapter is in
#: place. Legacy ``check_rep`` inference is weaker than modern
#: ``check_vma`` (e.g. it cannot see replication through a
#: ``jax.grad``-of-psum), so callers that rely on the stronger
#: inference gate on this flag.
LEGACY_SHARD_MAP = False


def _install_shard_map() -> None:
    global LEGACY_SHARD_MAP
    if getattr(jax, "shard_map", None) is not None:
        return
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - no known runtime hits this
        return
    LEGACY_SHARD_MAP = True

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  **kwargs):
        kwargs.pop("axis_names", None)  # legacy maps over all mesh axes
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_vma, **kwargs)

    shard_map.__doc__ = _legacy.__doc__
    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if getattr(jax.lax, "axis_size", None) is not None:
        return

    def axis_size(axis_name):
        # psum of a Python literal constant-folds to the (static) axis
        # size — the documented pre-axis_size spelling of the same query
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


_install_shard_map()
_install_axis_size()
