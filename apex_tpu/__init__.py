"""apex_tpu — TPU-native training-acceleration framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of apex
(kexinyu/apex, a fork of NVIDIA/apex):

- ``apex_tpu.amp``          — mixed-precision policies O0–O3 + functional
  dynamic loss scaling (reference: apex/amp/* (U)).
- ``apex_tpu.multi_tensor`` — flat-buffer pytree packing, the TPU analogue of
  apex's multi_tensor_apply + apex_C flatten/unflatten (U).
- ``apex_tpu.kernels``      — Pallas TPU kernels: fused LayerNorm/RMSNorm,
  scaled-masked softmax, flash attention, fused dense/MLP, Welford stats,
  fused optimizer sweeps (reference: csrc/* (U)).
- ``apex_tpu.optimizers``   — FusedAdam/FusedLAMB/FusedSGD/FusedNovoGrad/
  FusedAdagrad, LARC, ZeRO-style DistributedFusedAdam
  (reference: apex/optimizers/*, apex/contrib/optimizers/* (U)).
- ``apex_tpu.parallel``     — data-parallel runtime + SyncBatchNorm
  (reference: apex/parallel/* (U)).
- ``apex_tpu.transformer``  — tensor/sequence/pipeline parallelism over a
  device mesh (reference: apex/transformer/* (U)).
- ``apex_tpu.mesh``         — the single first-class communication backend:
  mesh axes over ICI/DCN + XLA collectives, replacing NCCL process groups.
- ``apex_tpu.data``         — native prefetching data loaders (C++ host
  runtime, csrc/host_runtime.cpp).
- ``apex_tpu.profiler``     — tracing/metrics subsystem (xprof hooks,
  per-step timing, structured metrics).
- ``apex_tpu.serving``      — static-shape continuous-batching inference
  engine (slot engine + scheduler).
- ``apex_tpu.telemetry``    — system-wide observability: metrics
  registry, per-request span timelines, recompile sentinel, live
  ``/metrics`` endpoint.

Citation convention: ``(U)`` paths refer to the upstream apex layout as
documented in SURVEY.md (the reference mount was empty at survey time).
"""

__version__ = "0.1.0"

try:
    from apex_tpu import _compat  # noqa: F401  (jax.shard_map shim)
    from apex_tpu import mesh  # noqa: F401
except ImportError:
    # No working jax (lint-only CI, a tree too broken to import): the
    # stdlib-only corners (apex_tpu.analysis) stay usable; every
    # jax-backed subpackage raises with the cause on first access via
    # __getattr__ below.
    pass

__all__ = [
    "mesh",
    "amp",
    "multi_tensor",
    "kernels",
    "optimizers",
    "parallel",
    "transformer",
    "contrib",
    "checkpoint",
    "data",
    "normalization",
    "profiler",
    "fp16_utils",
    "mlp",
    "fused_dense",
    "rnn",
    "reparameterization",
    "models",
    "serving",
    "telemetry",
    "testing",
    "capabilities",
    "has_capability",
    "__version__",
]


def __getattr__(name):
    # Lazy subpackage imports keep `import apex_tpu` light and avoid
    # touching jax backends at import time.
    if name in ("capabilities", "has_capability"):
        import importlib

        mod = importlib.import_module("apex_tpu._capabilities")
        return getattr(mod, name)
    if name in __all__:
        import importlib

        try:
            return importlib.import_module(f"apex_tpu.{name}")
        except ModuleNotFoundError as e:
            if e.name == f"apex_tpu.{name}":
                raise AttributeError(
                    f"module 'apex_tpu' has no attribute {name!r} ({e})"
                ) from e
            # the subpackage exists but a dependency (jax) does not —
            # report the real missing module, not a fake attribute
            raise
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")
