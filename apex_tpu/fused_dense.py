"""FusedDense / FusedDenseGeluDense — apex/fused_dense/fused_dense.py (U)
over csrc/fused_dense_cuda.cu (U).

GEMM+bias (and GEMM+bias+GELU+GEMM+bias) as single fused calls. As with
:mod:`apex_tpu.mlp`, XLA performs the epilogue fusion the CUDA code does by
hand, so these are API-parity modules over the jnp chain.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def fused_dense(x, kernel, bias=None):
    """y = x @ kernel + bias (``fused_dense_function`` (U))."""
    y = jnp.matmul(x, kernel)
    return y if bias is None else y + bias


def fused_dense_gelu_dense(x, kernel1, bias1, kernel2, bias2):
    """x @ W1 + b1 → gelu → @ W2 + b2 (``FusedDenseGeluDense`` (U))."""
    h = jax.nn.gelu(fused_dense(x, kernel1, bias1), approximate=True)
    return fused_dense(h, kernel2, bias2)


def _linear_init(key, fan_in, fan_out, dtype):
    bound = 1.0 / fan_in ** 0.5
    return jax.random.uniform(key, (fan_in, fan_out), dtype, -bound, bound)


@dataclasses.dataclass(frozen=True)
class FusedDense:
    in_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    def init(self, key):
        p = {"kernel": _linear_init(
            key, self.in_features, self.out_features, self.param_dtype)}
        if self.bias:
            p["bias"] = jnp.zeros((self.out_features,), self.param_dtype)
        return p

    def apply(self, params, x):
        return fused_dense(x, params["kernel"], params.get("bias"))


@dataclasses.dataclass(frozen=True)
class FusedDenseGeluDense:
    in_features: int
    intermediate_features: int
    out_features: int
    param_dtype: jnp.dtype = jnp.float32

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {
            "fc1": {"kernel": _linear_init(
                k1, self.in_features, self.intermediate_features,
                self.param_dtype),
                "bias": jnp.zeros((self.intermediate_features,),
                                  self.param_dtype)},
            "fc2": {"kernel": _linear_init(
                k2, self.intermediate_features, self.out_features,
                self.param_dtype),
                "bias": jnp.zeros((self.out_features,), self.param_dtype)},
        }

    def apply(self, params, x):
        return fused_dense_gelu_dense(
            x, params["fc1"]["kernel"], params["fc1"]["bias"],
            params["fc2"]["kernel"], params["fc2"]["bias"])
