"""Schema-constrained decoding — a byte-level JSON pushdown automaton.

The sampling step takes a per-slot vocab mask
(:func:`apex_tpu.serving.sampling.filter_logits` / ``draw_slots``); this
module is the host half: a small PDA over the byte-level vocab
(:class:`~apex_tpu.serving.api.tokenizer.ByteTokenizer` — token id ==
byte) whose current state yields the set of allowed next bytes. The
scheduler drives it opaquely through the
:class:`apex_tpu.serving.request.Request` ``constraint`` protocol —
``reset()`` at (re-)admission, ``allowed_tokens()`` uploaded as the
slot's mask with each chunk dispatch, ``advance(token)`` per emitted
token, ``done`` finishing the request (reason ``"stop"``) the moment
the value closes — so the emitted stream is ALWAYS a parseable,
schema-shaped JSON value, whatever the model's logits wanted.

Supported schema subset (compiled structurally, no ``$ref``):
``object`` (every declared property emitted, declaration order, no
whitespace), ``array`` (``items`` + ``minItems``/``maxItems``),
``string`` (printable-ASCII body, ``maxLength``), ``integer`` /
``number``, ``boolean``, ``null``, and ``enum`` of JSON literals.
``schema=None`` is OpenAI ``json_object`` mode: any JSON object, free
keys/values, bounded by the ``max_*`` knobs. String/number/array/depth
bounds force closure, so constrained generation terminates within a
bounded token count instead of rambling to the budget.

Stdlib-only by contract (the api dependency-free test imports this with
jax/numpy purged); masks stay token-id lists — the engine turns them
into device arrays.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

#: printable-ASCII string-body bytes: 0x20..0x7E minus '"' and '\'
#: (escape sequences are excluded from generation — every emitted
#: string byte is literal, which keeps the automaton regular and the
#: output trivially valid JSON)
_STR_BYTES = frozenset(b for b in range(0x20, 0x7F)
                       if b not in (0x22, 0x5C))
_DIGITS = frozenset(range(0x30, 0x3A))
_QUOTE, _COMMA, _COLON, _MINUS, _DOT = 0x22, 0x2C, 0x3A, 0x2D, 0x2E
_LBRACE, _RBRACE, _LBRACKET, _RBRACKET = 0x7B, 0x7D, 0x5B, 0x5D

#: frame.step outcomes beyond consumed(True)/cannot(False): the frame
#: restructured the stack and the byte must be retried on the new top
_RETRY = "retry"


class _Machine:
    """Frame stack. ``allowed()`` unions byte sets walking down from
    the top through frames that could end here (a complete number can
    be followed by its parent's ``,`` / ``}``); ``feed()`` pops
    completed frames until one consumes the byte."""

    __slots__ = ("stack",)

    def __init__(self, frames: List[Any]):
        self.stack = list(reversed(frames))

    def allowed(self) -> Set[int]:
        out: Set[int] = set()
        for fr in reversed(self.stack):
            out |= fr.inner_allowed(self)
            if not fr.can_end():
                break
        return out

    def feed(self, b: int) -> None:
        for _ in range(64):  # bounded restructure/pop chain
            if not self.stack:
                raise ValueError(
                    f"byte {b!r} after the constrained value closed")
            fr = self.stack[-1]
            r = fr.step(self, b)
            if r is True:
                return
            if r == _RETRY:
                continue
            if fr.can_end():
                self.stack.pop()
                continue
            raise ValueError(
                f"byte {bytes([b])!r} not allowed by the constraint "
                f"(allowed: {sorted(self.allowed())})")
        raise RuntimeError("constraint restructure chain did not land")

    def can_end_now(self) -> bool:
        """Every frame on the stack could end at this point — the
        value parsed so far is complete (a terminator/end signal would
        be legal)."""
        return all(f.can_end() for f in self.stack)

    @property
    def done(self) -> bool:
        return not self.stack or (
            not self.allowed() and self.can_end_now())


class _Lit:
    """Forced literal bytes (structure: braces, fixed keys, null)."""

    __slots__ = ("data", "i")

    def __init__(self, data: bytes):
        self.data, self.i = data, 0

    def inner_allowed(self, m) -> Set[int]:
        return {self.data[self.i]} if self.i < len(self.data) else set()

    def can_end(self) -> bool:
        return self.i >= len(self.data)

    def step(self, m, b):
        if self.i < len(self.data) and b == self.data[self.i]:
            self.i += 1
            if self.i == len(self.data):
                m.stack.pop()
            return True
        return False


class _Trie:
    """One of several literal byte strings (enums, true/false). NOT
    assumed prefix-free: after consuming a prefix that completes one
    option but could extend into another (numeric enums — ``1`` vs
    ``12``), the frame ``can_end`` (the parent's terminator, or the
    end token, closes the shorter option) while still offering the
    longer one's next byte."""

    __slots__ = ("cands", "i")

    def __init__(self, options: Sequence[bytes]):
        self.cands = [bytes(o) for o in options]
        self.i = 0

    def inner_allowed(self, m) -> Set[int]:
        return {o[self.i] for o in self.cands if len(o) > self.i}

    def can_end(self) -> bool:
        return any(len(o) == self.i for o in self.cands)

    def step(self, m, b):
        nxt = [o for o in self.cands if len(o) > self.i and o[self.i] == b]
        if not nxt:
            return False
        self.cands = nxt
        self.i += 1
        if all(len(o) == self.i for o in self.cands):
            m.stack.pop()  # no option can extend — the value is closed
        return True


class _Str:
    """String BODY + closing quote (the opening quote is a _Lit)."""

    __slots__ = ("n", "max_len")

    def __init__(self, max_len: int):
        self.n, self.max_len = 0, max_len

    def inner_allowed(self, m) -> Set[int]:
        out = {_QUOTE}
        if self.n < self.max_len:
            out |= _STR_BYTES
        return out

    def can_end(self) -> bool:
        return False

    def step(self, m, b):
        if b == _QUOTE:
            m.stack.pop()
            return True
        if self.n < self.max_len and b in _STR_BYTES:
            self.n += 1
            return True
        return False


class _Num:
    """JSON number: optional '-', int part (no leading zeros), and for
    non-integers an optional '.digits' fraction — digit counts bounded
    so the value cannot ramble to the token budget. Complete numbers
    ``can_end``: the terminator byte belongs to the parent frame."""

    __slots__ = ("integer", "max_int", "max_frac", "neg", "int_digits",
                 "int_zero", "frac", "frac_digits")

    def __init__(self, integer: bool, max_int: int, max_frac: int):
        self.integer, self.max_int, self.max_frac = \
            integer, max_int, max_frac
        self.neg = self.frac = self.int_zero = False
        self.int_digits = self.frac_digits = 0

    def inner_allowed(self, m) -> Set[int]:
        if self.frac:
            return set(_DIGITS) if self.frac_digits < self.max_frac \
                else set()
        if self.int_digits == 0:
            return set(_DIGITS) | ({_MINUS} if not self.neg else set())
        out: Set[int] = set()
        if not self.int_zero and self.int_digits < self.max_int:
            out |= _DIGITS
        if not self.integer:
            out.add(_DOT)
        return out

    def can_end(self) -> bool:
        if self.int_digits < 1:
            return False
        return not self.frac or self.frac_digits >= 1

    def step(self, m, b):
        if self.frac:
            if b in _DIGITS and self.frac_digits < self.max_frac:
                self.frac_digits += 1
                return True
            return False
        if self.int_digits == 0:
            if b == _MINUS and not self.neg:
                self.neg = True
                return True
            if b in _DIGITS:
                self.int_zero = b == 0x30
                self.int_digits = 1
                return True
            return False
        if b in _DIGITS and not self.int_zero \
                and self.int_digits < self.max_int:
            self.int_digits += 1
            return True
        if b == _DOT and not self.integer:
            self.frac = True
            return True
        return False


class _Arr:
    """Array body after '[': items from a factory, ',' between, ']'
    once ``min_items`` are in (allowed at start when ``min_items`` is
    0)."""

    __slots__ = ("item_make", "min_items", "max_items", "started",
                 "expect_item", "at_start")

    def __init__(self, item_make, min_items: int, max_items: int):
        self.item_make = item_make
        self.min_items, self.max_items = min_items, max_items
        self.started = 0
        self.expect_item = True
        self.at_start = True

    def inner_allowed(self, m) -> Set[int]:
        if self.expect_item:
            out = (set(_first(self.item_make()))
                   if self.started < self.max_items else set())
            if self.at_start and self.min_items == 0:
                out.add(_RBRACKET)
            return out
        out: Set[int] = set()
        if self.started < self.max_items:
            out.add(_COMMA)
        if self.started >= self.min_items:
            out.add(_RBRACKET)
        return out

    def can_end(self) -> bool:
        return False

    def step(self, m, b):
        if self.expect_item:
            if self.at_start and self.min_items == 0 and b == _RBRACKET:
                m.stack.pop()
                return True
            if self.started >= self.max_items:  # maxItems 0: only ']'
                return False
            self.expect_item = False
            self.at_start = False
            self.started += 1
            m.stack.extend(reversed(self.item_make()))
            return _RETRY
        if b == _COMMA and self.started < self.max_items:
            self.expect_item = True
            return True
        if b == _RBRACKET and self.started >= self.min_items:
            m.stack.pop()
            return True
        return False


class _Obj:
    """Generic object body after '{' (``json_object`` mode): free
    string keys, generic values, key count bounded."""

    __slots__ = ("opts", "depth", "state", "count")

    def __init__(self, opts: "_Options", depth: int):
        self.opts, self.depth = opts, depth
        self.state = "start"
        self.count = 0

    def inner_allowed(self, m) -> Set[int]:
        return {
            "start": {_QUOTE, _RBRACE},
            "key": {_QUOTE},
            "colon": {_COLON},
            "value": set(_first(_value_frames(self.opts, self.depth))),
            "after": ({_COMMA} if self.count < self.opts.max_keys
                      else set()) | {_RBRACE},
        }[self.state]

    def can_end(self) -> bool:
        return False

    def step(self, m, b):
        if self.state in ("start", "key"):
            if self.state == "start" and b == _RBRACE:
                m.stack.pop()
                return True
            if b == _QUOTE:
                self.count += 1
                self.state = "colon"
                m.stack.append(_Str(self.opts.max_string_len))
                return True
            return False
        if self.state == "colon":
            if b == _COLON:
                self.state = "value"
                return True
            return False
        if self.state == "value":
            self.state = "after"
            m.stack.extend(reversed(_value_frames(self.opts, self.depth)))
            return _RETRY
        # after a value: another key, or close
        if b == _COMMA and self.count < self.opts.max_keys:
            self.state = "key"
            return True
        if b == _RBRACE:
            m.stack.pop()
            return True
        return False


class _Val:
    """Generic JSON value — branch on the first byte, then replace
    self with the chosen production."""

    __slots__ = ("opts", "depth")

    def __init__(self, opts: "_Options", depth: int):
        self.opts, self.depth = opts, depth

    def inner_allowed(self, m) -> Set[int]:
        out = {_QUOTE, _MINUS, 0x74, 0x66, 0x6E} | _DIGITS  # " - t f n
        if self.depth > 0:
            out |= {_LBRACE, _LBRACKET}
        return out

    def can_end(self) -> bool:
        return False

    def step(self, m, b):
        o = self.opts
        repl: Optional[List[Any]] = None
        if b == _QUOTE:
            repl = [_Lit(b'"'), _Str(o.max_string_len)]
        elif b == _MINUS or b in _DIGITS:
            repl = [_Num(False, o.max_int_digits, o.max_frac_digits)]
        elif b in (0x74, 0x66):  # t / f
            repl = [_Trie([b"true", b"false"])]
        elif b == 0x6E:  # n
            repl = [_Lit(b"null")]
        elif b == _LBRACE and self.depth > 0:
            repl = [_Lit(b"{"), _Obj(o, self.depth - 1)]
        elif b == _LBRACKET and self.depth > 0:
            repl = [_Lit(b"["),
                    _Arr(lambda: _value_frames(o, self.depth - 1),
                         0, o.max_items)]
        if repl is None:
            return False
        m.stack.pop()
        m.stack.extend(reversed(repl))
        return _RETRY


def _value_frames(opts: "_Options", depth: int) -> List[Any]:
    return [_Val(opts, depth)]


def _first(frames: List[Any]) -> Set[int]:
    """FIRST set of a production: the allowed bytes of a scratch
    machine holding fresh frames."""
    return _Machine(list(frames)).allowed()


class _Options:
    """Generation bounds — they force closure (a finite token count)
    whatever the logits prefer."""

    __slots__ = ("max_string_len", "max_int_digits", "max_frac_digits",
                 "max_items", "max_keys", "max_depth")

    def __init__(self, max_string_len=48, max_int_digits=9,
                 max_frac_digits=6, max_items=4, max_keys=4,
                 max_depth=3):
        self.max_string_len = max_string_len
        self.max_int_digits = max_int_digits
        self.max_frac_digits = max_frac_digits
        self.max_items = max_items
        self.max_keys = max_keys
        self.max_depth = max_depth


def _compile(schema: Optional[Dict[str, Any]],
             opts: _Options) -> Callable[[], List[Any]]:
    """Schema → factory of fresh frame lists (factories because arrays
    instantiate their item production per element, and ``reset()``
    rebuilds the whole machine)."""
    if schema is None:
        # json_object mode: any JSON object
        return lambda: [_Lit(b"{"), _Obj(opts, opts.max_depth)]
    if "enum" in schema:
        lits = [json.dumps(v, separators=(",", ":")).encode("utf-8")
                for v in schema["enum"]]
        if not lits:
            raise ValueError("enum schema needs at least one value")
        return lambda: [_Trie(lits)]
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties") or {}
        if not props:
            return lambda: [_Lit(b"{}")]
        parts: List[Any] = []  # bytes literals interleaved with factories
        for i, (key, sub) in enumerate(props.items()):
            prefix = ("{" if i == 0 else ",") + json.dumps(key) + ":"
            parts.append(prefix.encode("utf-8"))
            parts.append(_compile(sub, opts))
        parts.append(b"}")

        def make() -> List[Any]:
            frames: List[Any] = []
            for p in parts:
                if isinstance(p, bytes):
                    frames.append(_Lit(p))
                else:
                    frames.extend(p())
            return frames

        return make
    if t == "array":
        item = _compile(schema.get("items"), opts)
        mn = max(0, int(schema.get("minItems", 0)))  # JSON Schema default
        mx = int(schema.get("maxItems", max(mn, opts.max_items)))
        if mx < mn:
            raise ValueError(f"maxItems {mx} < minItems {mn}")
        return lambda: [_Lit(b"["), _Arr(item, mn, mx)]
    if t == "string":
        mx = min(int(schema.get("maxLength", opts.max_string_len)),
                 opts.max_string_len)
        return lambda: [_Lit(b'"'), _Str(mx)]
    if t == "integer":
        return lambda: [_Num(True, opts.max_int_digits,
                             opts.max_frac_digits)]
    if t == "number":
        return lambda: [_Num(False, opts.max_int_digits,
                             opts.max_frac_digits)]
    if t == "boolean":
        return lambda: [_Trie([b"true", b"false"])]
    if t == "null":
        return lambda: [_Lit(b"null")]
    # unknown/omitted type: any bounded JSON value
    return lambda: [_Val(opts, opts.max_depth)]


def _value_bound(opts: _Options, depth: int) -> int:
    """Worst-case byte count of one generic JSON value at ``depth``."""
    scalar = max(2 + opts.max_string_len,                 # "…"
                 1 + opts.max_int_digits                  # -ddd…
                 + 1 + opts.max_frac_digits,              # .ddd…
                 5)                                       # false
    if depth <= 0:
        return scalar
    inner = _value_bound(opts, depth - 1)
    obj = 2 + opts.max_keys * (2 + opts.max_string_len + 1 + inner + 1)
    arr = 2 + opts.max_items * (inner + 1)
    return max(scalar, obj, arr)


def _schema_bound(schema: Optional[Dict[str, Any]],
                  opts: _Options) -> int:
    """Worst-case byte count of a value matching ``schema`` under the
    closure bounds — the token budget that guarantees the constrained
    value completes (every grammar branch is bounded by construction)."""
    if schema is None:
        # json_object mode: an object of generic values
        return 2 + opts.max_keys * (
            2 + opts.max_string_len + 1
            + _value_bound(opts, opts.max_depth) + 1)
    if "enum" in schema:
        return max((len(json.dumps(v, separators=(",", ":"))
                        .encode("utf-8")) for v in schema["enum"]),
                   default=0)
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties") or {}
        if not props:
            return 2
        total = 1  # final '}'
        for i, (key, sub) in enumerate(props.items()):
            prefix = ("{" if i == 0 else ",") + json.dumps(key) + ":"
            total += len(prefix.encode("utf-8")) + _schema_bound(sub,
                                                                 opts)
        return total
    if t == "array":
        mn = max(0, int(schema.get("minItems", 0)))
        mx = int(schema.get("maxItems", max(mn, opts.max_items)))
        return 2 + mx * (_schema_bound(schema.get("items"), opts) + 1)
    if t == "string":
        return 2 + min(int(schema.get("maxLength", opts.max_string_len)),
                       opts.max_string_len)
    if t == "integer":
        return 1 + opts.max_int_digits
    if t == "number":
        return 1 + opts.max_int_digits + 1 + opts.max_frac_digits
    if t == "boolean":
        return 5
    if t == "null":
        return 4
    return _value_bound(opts, opts.max_depth)


class JsonSchemaConstraint:
    """The ``Request.constraint`` implementation for JSON output over a
    byte-level vocab (token id == byte id).

    >>> c = JsonSchemaConstraint({"type": "object", "properties":
    ...     {"name": {"type": "string"}, "age": {"type": "integer"}}})
    >>> c.allowed_tokens()   # [ord('{')] — the object must open
    >>> c.advance(ord('{')); c.done
    False

    ``schema=None`` is ``json_object`` mode (any JSON object). The
    scheduler calls ``reset()`` at every (re-)admission — fault replay
    re-derives the byte stream, and the automaton follows it
    deterministically.

    ``end_token_id`` (a NON-byte id, >= 256 — the tokenizer's eos) is
    offered in the allowed set whenever the value parsed so far is
    already complete, so the model can CHOOSE to stop a value whose
    grammar could also continue — without it a top-level bare
    ``integer``/``number`` schema has no terminator byte and is forced
    to its digit bounds (self-closing values — objects, arrays,
    strings, enums — terminate structurally either way)."""

    def __init__(self, schema: Optional[Dict[str, Any]] = None, *,
                 max_string_len: int = 48, max_int_digits: int = 9,
                 max_frac_digits: int = 6, max_items: int = 4,
                 max_keys: int = 4, max_depth: int = 3,
                 end_token_id: Optional[int] = None):
        if end_token_id is not None and end_token_id < 256:
            raise ValueError(
                f"end_token_id must be a non-byte id (>= 256), got "
                f"{end_token_id} — a byte-range end token would alias "
                f"a JSON byte the grammar may need")
        self.schema = schema
        self.end_token_id = end_token_id
        self._opts = _Options(
            max_string_len=max_string_len, max_int_digits=max_int_digits,
            max_frac_digits=max_frac_digits, max_items=max_items,
            max_keys=max_keys, max_depth=max_depth)
        self._make = _compile(schema, self._opts)
        self._machine = _Machine(self._make())

    def reset(self) -> None:
        self._machine = _Machine(self._make())

    def token_bound(self) -> int:
        """Worst-case number of tokens (bytes) the constrained value
        can need before it closes — the ``max_tokens`` floor that
        makes the always-valid guarantee hold (the closure bounds make
        every branch finite). One extra token covers an end-token
        finish."""
        return _schema_bound(self.schema, self._opts) + (
            1 if self.end_token_id is not None else 0)

    def allowed_tokens(self) -> List[int]:
        allowed = sorted(self._machine.allowed())
        if self.end_token_id is not None and self._machine.stack \
                and self._machine.can_end_now():
            allowed.append(self.end_token_id)
        if not allowed and not self.done:
            raise RuntimeError(
                "constraint automaton stuck: no allowed bytes and not "
                "done (schema compile bug)")
        return allowed

    def advance(self, token: int) -> None:
        token = int(token)
        if self.end_token_id is not None and token == self.end_token_id:
            if not self._machine.can_end_now():
                raise ValueError(
                    "end token emitted while the constrained value is "
                    "incomplete")
            self._machine.stack.clear()
            return
        self._machine.feed(token)

    @property
    def done(self) -> bool:
        return self._machine.done
