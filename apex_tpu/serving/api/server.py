"""The HTTP front end — OpenAI-compatible serving over the scheduler.

``ApiServer`` owns a warmed :class:`~apex_tpu.serving.scheduler.
Scheduler` and splits the work across threads the way the stack's
thread-safety demands: the scheduler is single-threaded, so ONE driver
thread does everything that touches it (submit, tick, event routing),
while the stdlib ``ThreadingHTTPServer`` handlers (one thread per
connection, the ``telemetry.http`` pattern) only parse/validate
requests, hand them over through a queue, and stream what comes back.

Routes::

    POST /v1/chat/completions   chat template → tokens → engine, SSE
    POST /v1/completions        text or raw token-id prompt
    GET  /v1/models             the single served model
    GET  /healthz               the scheduler's live health machine
                                (same callback shape MetricsServer
                                takes — 200 ok/degraded, 503 otherwise)
    GET  /slo                   the SLO observatory snapshot (objective
                                states, burn rates, budget remaining,
                                percentiles) when the scheduler — or
                                every fleet replica — runs an
                                SLOMonitor; 404 otherwise

Error mapping rides the PR-5 resilience surface: queue backpressure /
flood (:class:`~apex_tpu.serving.scheduler.QueueFull`) → 429 with
``Retry-After`` from the scheduler's drain estimate; a failed health
machine (:class:`~apex_tpu.serving.resilience.EngineFailed`) → 503;
validation → 400 with an OpenAI-shaped error body; a request that
finishes with the ``error`` reason (fault retries exhausted) → an SSE
``{"error": ...}`` event mid-stream or a 500 when buffered. Mid-stream
faults cannot duplicate SSE chunks: the scheduler's replay suppresses
re-derived tokens before they ever reach the event stream, and the
wire layer emits exactly one chunk per event (a retry in progress
surfaces as an SSE comment, which OpenAI clients ignore).

``n > 1`` fans one API request into n engine requests sharing the
prompt (per-choice seeds derive from the request seed), merged back
into one multi-choice response/stream. Stop strings compile to stop
token sequences (byte-level codec: the two are the same thing);
``response_format`` compiles to a
:class:`~apex_tpu.serving.api.constrain.JsonSchemaConstraint`.

Stdlib-only at import (the dependency-free test pins it): the
scheduler/resilience classes are only imported inside the driver, at
which point the caller has long since imported them to build the
engine this server wraps.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.serving.api import protocol
from apex_tpu.serving.api.constrain import JsonSchemaConstraint
from apex_tpu.serving.api.tokenizer import ByteTokenizer
from apex_tpu.serving.request import Request, SamplingParams
# stdlib-only by construction (the dependency-free test covers it):
# tenancy is pure host policy, no jax behind it
from apex_tpu.serving.tenancy import DEFAULT_TENANT, TenantThrottled

_ROUTES = ("chat", "completions", "models", "healthz", "other")


class _ApiMetrics:
    """Pre-bound per-route request counters + latency histograms, plus
    a (route, code) response counter — resolved once so handlers never
    do a label lookup per request."""

    def __init__(self, registry):
        req = registry.counter(
            "api_requests_total", "HTTP requests received, by route",
            labels=("route",))
        self.requests = {r: req.labels(route=r) for r in _ROUTES}
        self.responses = registry.counter(
            "api_responses_total",
            "HTTP responses sent, by route and status code",
            labels=("route", "code"))
        lat = registry.histogram(
            "api_request_seconds",
            "request receipt to response fully written (streams: last "
            "SSE byte), by route", labels=("route",))
        self.latency = {r: lat.labels(route=r) for r in _ROUTES}
        self.stream_tokens = registry.counter(
            "api_sse_tokens_total", "tokens streamed over SSE")


class _Submission:
    """One API request crossing the handler → driver boundary: the
    fanned engine requests, the merged per-choice event queue, and a
    one-slot reply carrying None (accepted) or an ApiError."""

    __slots__ = ("requests", "events", "reply")

    def __init__(self, requests: List[Request]):
        self.requests = requests
        #: (choice_index, kind, payload) — kind "event" carries a
        #: StreamEvent, "completion" the terminal Completion
        self.events: "queue.Queue[Tuple[int, str, Any]]" = queue.Queue()
        self.reply: "queue.Queue[Optional[protocol.ApiError]]" = \
            queue.Queue(1)


class ApiServer:
    """Serve the OpenAI surface over a warmed scheduler — or a
    :class:`~apex_tpu.serving.fleet.Router` over N replicas (the
    router duck-types the scheduler surface; 429s then mean "every
    routable replica is saturated", 503s "no replica left standing",
    and ``/healthz`` answers from the fleet aggregate) — until
    ``stop()``.

    >>> server = ApiServer(sched, ByteTokenizer(cfg.vocab_size),
    ...                    port=8000).start()
    >>> # curl localhost:8000/v1/chat/completions -d '{...}'
    >>> server.stop()
    """

    def __init__(self, scheduler, tokenizer: ByteTokenizer, *,
                 model: str = "apex-tpu-gpt", host: str = "127.0.0.1",
                 port: int = 0, registry=None,
                 health: Optional[Callable[[], Tuple[int, str]]] = None,
                 max_tokens_default: int = 16,
                 request_timeout_s: float = 120.0,
                 poll_interval_s: float = 0.0005,
                 prefix_templates: Optional[Sequence[Any]] = None):
        self.scheduler = scheduler
        self.tokenizer = tokenizer
        self.model = model
        #: shared-prompt templates (strings, or token-id lists)
        #: registered into the engine's prefix pool at :meth:`start` —
        #: the wire-level surface of prefix reuse: any request whose
        #: prompt starts with a registered template admits by pooled
        #: K/V copy + tail-only prefill, transparently
        self.prefix_templates = list(prefix_templates or ())
        self.max_tokens_default = max_tokens_default
        self.request_timeout_s = request_timeout_s
        self.poll_interval_s = poll_interval_s
        #: /healthz callback (status, body) — pass
        #: ``sched.health.healthz`` to answer from the live state
        #: machine; defaults to it when the scheduler has one
        self.health = health if health is not None else getattr(
            getattr(scheduler, "health", None), "healthz", None)
        self.metrics = None if registry is None else _ApiMetrics(registry)
        self._host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._driver: Optional[threading.Thread] = None
        self._running = False
        self._submit_q: "queue.Queue[_Submission]" = queue.Queue()
        #: child request id → (submission event queue, choice index);
        #: driver-thread-owned
        self._live: Dict[str, Tuple["queue.Queue", int]] = {}
        #: children whose fan failed mid-submit and lost their routes —
        #: the driver discards their completions so nothing leaks
        self._orphans: set = set()
        #: set when the driver thread dies on an unexpected exception;
        #: handlers answer 503 immediately instead of blocking out
        #: their timeout against a dead queue
        self._driver_error: Optional[str] = None
        self._counter_lock = threading.Lock()
        self._counter = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ApiServer":
        if self._httpd is not None:
            return self
        # fleet-aware registration: a Router registers the template
        # into EVERY replica's pool; a plain Scheduler into its engine
        register = getattr(self.scheduler, "register_prefix",
                           None) or self.scheduler.engine.register_prefix
        for tpl in self.prefix_templates:
            # BEFORE the driver thread exists — registration is the
            # last main-thread device work (a compiled pool insert)
            toks = (self.tokenizer.encode(tpl) if isinstance(tpl, str)
                    else [int(t) for t in tpl])
            register(toks)
        self._running = True
        self._driver = threading.Thread(
            target=self._drive, name="apex-tpu-api-driver", daemon=True)
        self._driver.start()
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port),
            _make_handler(self))
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever,
                         name="apex-tpu-api-http", daemon=True).start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._running = False
        if self._driver is not None:
            self._driver.join(timeout=10.0)
            self._driver = None

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def _next_id(self) -> int:
        with self._counter_lock:
            self._counter += 1
            return self._counter

    def slo_status(self) -> Optional[Dict[str, Any]]:
        """The ``/slo`` payload: the scheduler's SLO-observatory
        status, or — when serving a fleet Router — the router's
        aggregate (which folds every replica's monitor plus the
        fleet-merged percentiles). None when no monitor is wired, so
        the route 404s exactly like an unwired debug route."""
        agg = getattr(self.scheduler, "slo_status", None)
        if agg is not None:  # fleet Router aggregate
            return agg()
        mon = getattr(self.scheduler, "slo", None)
        return None if mon is None else mon.status()

    # -- the driver thread (sole owner of the scheduler) --------------------

    def _drive(self) -> None:
        try:
            self._drive_loop()
        except BaseException as e:  # the sole scheduler owner died —
            # leave a diagnosis, fail fast instead of hanging clients
            import traceback

            self._driver_error = f"{type(e).__name__}: {e}"
            traceback.print_exc()
            while True:
                try:
                    sub = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                sub.reply.put(protocol.ApiError(
                    503, f"api driver crashed ({self._driver_error})",
                    err_type="server_error", code="driver_crashed"))

    def _drive_loop(self) -> None:
        from apex_tpu.serving.resilience import EngineFailed
        from apex_tpu.serving.scheduler import QueueFull

        sched = self.scheduler
        while self._running:
            progressed = False
            while True:
                try:
                    sub = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                self._submit(sub, QueueFull, EngineFailed)
                progressed = True
            if not sched.idle():
                sched.step()
                progressed = True
            for ev in sched.pop_events():
                route = self._live.get(ev.request_id)
                if route is not None:
                    route[0].put((route[1], "event", ev))
            # route terminal completions and POP them — the batch-mode
            # contract (sched.completions accumulates) would leak one
            # Completion per request in a long-running server
            for rid in [r for r in self._live
                        if r in sched.completions]:
                q, idx = self._live.pop(rid)
                q.put((idx, "completion", sched.completions.pop(rid)))
            for rid in [r for r in self._orphans
                        if r in sched.completions]:
                self._orphans.discard(rid)
                sched.completions.pop(rid)
            if not progressed:
                time.sleep(self.poll_interval_s)

    def _submit(self, sub: _Submission, QueueFull, EngineFailed) -> None:
        sched = self.scheduler
        # terminal health is a 503, never a capacity 429: a failed
        # engine — or a fleet with NO surviving replica — is not
        # "try again later"
        if getattr(getattr(sched, "health", None), "state", None) \
                == "failed":
            sub.reply.put(protocol.ApiError(
                503, "engine health is failed; not accepting requests",
                err_type="server_error", code="engine_failed"))
            return
        # all-or-nothing pre-flight: an n>1 fan must not half-land when
        # the queue is nearly full. can_accept is fleet-aware: a
        # Router answers for the ROUTABLE replicas' combined headroom,
        # a plain Scheduler for its own queue
        if not sched.can_accept(len(sub.requests)):
            sub.reply.put(protocol.ApiError(
                429, "queue at capacity",
                err_type="rate_limit_error", code="queue_full",
                retry_after_s=sched.overload_hint_s()))
            return
        for i, r in enumerate(sub.requests):
            self._live[r.request_id] = (sub.events, i)

        def fail(i: int, err: protocol.ApiError) -> None:
            # children already queued keep running as orphans — their
            # routes are torn down and the driver discards their
            # completions when they land
            for rr in sub.requests:
                self._live.pop(rr.request_id, None)
            self._orphans.update(
                rr.request_id for rr in sub.requests[:i])
            sub.reply.put(err)

        for i, r in enumerate(sub.requests):
            try:
                sched.submit(r)
            except TenantThrottled as e:
                # per-tenant token budget exhausted: 429 with the
                # bucket's refill time as Retry-After — tenant-wide,
                # so unlike QueueFull no other replica is worth trying
                fail(i, protocol.ApiError(
                    429, str(e), err_type="rate_limit_error",
                    code="tenant_rate_limited",
                    retry_after_s=e.retry_after_s))
                return
            except QueueFull as e:  # an injected flood / a race lost
                fail(i, protocol.ApiError(
                    429, str(e), err_type="rate_limit_error",
                    code="queue_full", retry_after_s=e.retry_after_s))
                return
            except EngineFailed as e:
                fail(i, protocol.ApiError(
                    503, str(e), err_type="server_error",
                    code="engine_failed"))
                return
            except ValueError as e:
                fail(i, protocol.ApiError(400, str(e)))
                return
        sub.reply.put(None)

    # -- request building (handler threads; engine-free) --------------------

    def _resolve_adapter(self, model: str) -> int:
        """Map the request's ``model`` to a LoRA adapter row: a
        registered adapter name routes to its id, anything else —
        including the served base model name — routes to the pinned
        base adapter 0 (the model string is echoed either way, the
        OpenAI convention)."""
        names = getattr(self.scheduler.engine, "adapter_names", None)
        if not names:
            return 0
        return names.get(model, 0)

    def _build_requests(self, parsed: protocol.ParsedRequest,
                        base_id: str,
                        tenant: str = DEFAULT_TENANT
                        ) -> Tuple[List[Request], List[int]]:
        tok = self.tokenizer
        if parsed.messages is not None:
            prompt = tok.encode(
                protocol.render_chat_prompt(parsed.messages))
        elif parsed.prompt_tokens is not None:
            prompt = list(parsed.prompt_tokens)
            bad = [t for t in prompt
                   if not 0 <= t < tok.vocab_size]
            if bad:
                raise protocol.ApiError(
                    400, f"prompt token ids {bad[:8]} outside vocab "
                    f"[0, {tok.vocab_size})", param="prompt")
        else:
            prompt = tok.encode(parsed.prompt_text or "")
        if not prompt:
            raise protocol.ApiError(400, "prompt must not be empty",
                                    param="prompt")
        ecfg = self.scheduler.engine.engine_cfg
        limit = min(ecfg.max_prompt_len, ecfg.max_seq_len - 1)
        if len(prompt) > limit:
            raise protocol.ApiError(
                400, f"prompt is {len(prompt)} tokens; this server "
                f"admits at most {limit}", param="prompt",
                code="context_length_exceeded")
        room = ecfg.max_seq_len - len(prompt)
        max_tokens = min(parsed.max_tokens or self.max_tokens_default,
                         room)
        stops = [tuple(tok.encode(s)) for s in parsed.stop if s]
        stops += [tuple(s) for s in parsed.stop_token_ids]
        seed = parsed.seed
        if parsed.temperature > 0.0 and seed is None:
            # sampling needs a per-request PRNG stream; clients that
            # want reproducibility pass seed explicitly
            seed = self._next_id() * 1000003 % (2**31)
        # a byte-range eos (< 256) aliases a JSON byte: a constrained
        # value containing that byte would trip the device eos
        # mid-value and truncate the JSON — constrained requests only
        # stop via the grammar (or a non-byte eos, threaded as the
        # constraint's end token below)
        eos = tok.eos_token_id
        constrained_eos = (eos if eos is None or eos >= 256 else None)
        requests: List[Request] = []
        for i in range(parsed.n):
            constraint = None
            if parsed.response_format is not None:
                schema = None
                if parsed.response_format.get("type") == "json_schema":
                    schema = parsed.response_format["json_schema"][
                        "schema"]
                # per-choice instance: the automaton is stateful. The
                # `bounds` extension tightens the closure bounds so a
                # schema's worst case fits the token budget; the eos id
                # (when the tokenizer has one) lets the model terminate
                # a value whose grammar could also continue
                bounds = parsed.response_format.get("bounds") or {}
                # a byte-range eos would alias a JSON byte — only a
                # non-byte id can act as the value terminator
                end_id = (tok.eos_token_id
                          if tok.eos_token_id is not None
                          and tok.eos_token_id >= 256 else None)
                try:
                    constraint = JsonSchemaConstraint(
                        schema, end_token_id=end_id, **bounds)
                except (TypeError, ValueError) as e:
                    # structurally-a-dict but semantically invalid
                    # schemas (empty enum, maxItems < minItems, ...)
                    # surface at compile time — a client error, not a
                    # connection drop
                    raise protocol.ApiError(
                        400, f"response_format schema rejected: {e}",
                        param="response_format")
                if schema is not None \
                        and constraint.token_bound() > max_tokens:
                    # a budget below the schema's closure bound could
                    # truncate mid-value — the always-valid guarantee
                    # is enforced, not hoped for (json_object mode is
                    # exempt, matching OpenAI's documented may-truncate
                    # semantics)
                    raise protocol.ApiError(
                        400, f"response_format schema can need up to "
                        f"{constraint.token_bound()} tokens; "
                        f"max_tokens/context allows {max_tokens} — "
                        f"raise max_tokens or tighten "
                        f"response_format.bounds",
                        param="max_tokens",
                        code="max_tokens_below_schema_bound")
            sp = SamplingParams(
                temperature=parsed.temperature, top_k=parsed.top_k,
                top_p=parsed.top_p,
                seed=None if seed is None else seed + i)
            requests.append(Request(
                request_id=f"{base_id}-{i}", prompt=prompt,
                max_tokens=max_tokens, sampling=sp,
                eos_token_id=(constrained_eos if constraint is not None
                              else eos),
                stop=stops or None, constraint=constraint,
                tenant=tenant,
                adapter=self._resolve_adapter(parsed.model)))
        return requests, prompt


def _make_handler(server: ApiServer):
    tok = server.tokenizer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # silence per-request spam
            pass

        # -- plumbing -------------------------------------------------------

        def _reply(self, route: str, status: int, body: bytes,
                   ctype: str = "application/json",
                   retry_after_s: Optional[float] = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 str(max(1, int(retry_after_s + 0.999))))
            self.end_headers()
            self.wfile.write(body)
            m = server.metrics
            if m is not None:
                m.responses.labels(route=route, code=str(status)).inc()

        def _reply_error(self, route: str,
                         e: protocol.ApiError) -> None:
            self._reply(route, e.status,
                        json.dumps(e.body()).encode("utf-8"),
                        retry_after_s=e.retry_after_s)

        def _read_json(self) -> Dict[str, Any]:
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                return json.loads(raw.decode("utf-8"))
            except Exception:
                raise protocol.ApiError(
                    400, "request body must be valid JSON")

        # -- routes ---------------------------------------------------------

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                route = "healthz"
                if server.metrics is not None:
                    server.metrics.requests[route].inc()
                status, text = ((200, "ok\n") if server.health is None
                                else server.health())
                self._reply(route, status, text.encode("utf-8"),
                            ctype="text/plain; charset=utf-8")
            elif path == "/v1/models":
                route = "models"
                if server.metrics is not None:
                    server.metrics.requests[route].inc()
                # the base model plus every registered LoRA adapter —
                # an adapter's name IS a model id clients pass in
                # `model` to route their requests onto its weights
                data = [{"id": server.model, "object": "model",
                         "owned_by": "apex_tpu"}]
                names = getattr(server.scheduler.engine,
                                "adapter_names", None) or {}
                data += [{"id": n, "object": "model",
                          "owned_by": "apex_tpu",
                          "parent": server.model, "adapter": i}
                         for n, i in sorted(names.items(),
                                            key=lambda kv: kv[1])]
                body = {"object": "list", "data": data}
                self._reply(route, 200,
                            json.dumps(body).encode("utf-8"))
            elif path == "/slo":
                route = "other"
                if server.metrics is not None:
                    server.metrics.requests[route].inc()
                status = server.slo_status()
                if status is None:
                    self.send_error(
                        404, "no SLO monitor wired — construct the "
                        "scheduler with slo=SLOConfig(...)")
                    return
                self._reply(route, 200,
                            json.dumps(status, sort_keys=True,
                                       default=str).encode("utf-8"))
            else:
                self.send_error(404, "try /v1/chat/completions "
                                "/v1/completions /v1/models /healthz "
                                "/slo")

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            if path == "/v1/chat/completions":
                self._generate("chat")
            elif path == "/v1/completions":
                self._generate("completions")
            else:
                self.send_error(404, "try /v1/chat/completions "
                                "/v1/completions /v1/models /healthz")

        # -- generation -----------------------------------------------------

        def _generate(self, route: str) -> None:
            t0 = time.monotonic()
            m = server.metrics
            if m is not None:
                m.requests[route].inc()
            try:
                body = self._read_json()
                parsed = (protocol.parse_chat_request(body)
                          if route == "chat"
                          else protocol.parse_completion_request(body))
                rid = ("chatcmpl-" if route == "chat" else "cmpl-") \
                    + format(server._next_id(), "x")
                # tenant identity: the X-Tenant-Id header wins over
                # the OpenAI `user` field; anonymous traffic shares
                # the default tenant
                tenant = (self.headers.get("X-Tenant-Id")
                          or parsed.user or DEFAULT_TENANT)
                requests, prompt = server._build_requests(
                    parsed, rid, tenant=tenant)
            except protocol.ApiError as e:
                self._reply_error(route, e)
                return
            if server._driver_error is not None:
                self._reply_error(route, protocol.ApiError(
                    503, f"api driver crashed "
                    f"({server._driver_error})",
                    err_type="server_error", code="driver_crashed"))
                return
            sub = _Submission(requests)
            server._submit_q.put(sub)
            try:
                err = sub.reply.get(timeout=server.request_timeout_s)
            except queue.Empty:
                err = protocol.ApiError(
                    503, "driver did not accept the request in time",
                    err_type="server_error")
            if err is not None:
                self._reply_error(route, err)
                return
            created = int(time.time())
            try:
                if parsed.stream:
                    self._stream(route, rid, created, parsed, sub)
                else:
                    self._buffered(route, rid, created, parsed, sub,
                                   len(prompt))
            except (BrokenPipeError, ConnectionResetError, OSError):
                return  # client went away; engine side runs out
            finally:
                if m is not None:
                    m.latency[route].observe(time.monotonic() - t0)

        def _next_item(self, sub: _Submission):
            try:
                return sub.events.get(timeout=server.request_timeout_s)
            except queue.Empty:
                raise protocol.ApiError(
                    503, f"no progress in {server.request_timeout_s}s",
                    err_type="server_error", code="timeout")

        def _buffered(self, route: str, rid: str, created: int,
                      parsed: protocol.ParsedRequest, sub: _Submission,
                      n_prompt: int) -> None:
            comps: Dict[int, Any] = {}
            try:
                while len(comps) < parsed.n:
                    idx, kind, payload = self._next_item(sub)
                    if kind == "completion":
                        comps[idx] = payload
            except protocol.ApiError as e:
                self._reply_error(route, e)
                return
            if any(c.finish_reason == "error" for c in comps.values()):
                detail = "; ".join(
                    f"choice {i}: fault retries exhausted"
                    for i, c in sorted(comps.items())
                    if c.finish_reason == "error")
                self._reply_error(route, protocol.ApiError(
                    500, f"generation failed ({detail})",
                    err_type="server_error", code="generation_error"))
                return
            choices = []
            for i, comp in sorted(comps.items()):
                text = tok.decode(comp.tokens)
                if parsed.echo and parsed.prompt_text is not None:
                    text = parsed.prompt_text + text
                lp = None
                if parsed.logprobs:
                    dec = tok.stream_decoder()
                    triples = [(dec.push(t), t, l) for t, l in
                               zip(comp.tokens, comp.logprobs or [])]
                    lp = (protocol._chat_logprobs(triples)
                          if route == "chat"
                          else protocol._completion_logprobs(triples))
                kw = dict(
                    logprobs=lp,
                    token_ids=(list(comp.tokens)
                               if parsed.return_token_ids else None))
                fin = protocol.FINISH_REASON_MAP.get(
                    comp.finish_reason, comp.finish_reason)
                choices.append(
                    protocol.chat_choice(i, text, fin, **kw)
                    if route == "chat"
                    else protocol.completion_choice(i, text, fin, **kw))
            usage = protocol.usage_dict(
                n_prompt,
                sum(len(c.tokens) for c in comps.values()))
            build = (protocol.build_chat_response if route == "chat"
                     else protocol.build_completion_response)
            out = build(rid=rid, created=created, model=parsed.model,
                        choices=choices, usage=usage)
            self._reply(route, 200, json.dumps(out).encode("utf-8"))

        def _stream(self, route: str, rid: str, created: int,
                    parsed: protocol.ParsedRequest,
                    sub: _Submission) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            m = server.metrics
            if m is not None:
                m.responses.labels(route=route, code="200").inc()
            w = self.wfile
            mk = (protocol.chat_chunk if route == "chat"
                  else protocol.completion_chunk)

            def chunk(i, text, fin=None, lp=None, ids=None):
                kw: Dict[str, Any] = dict(
                    rid=rid, created=created, model=parsed.model,
                    index=i, finish_reason=fin, logprob=lp,
                    token_ids=ids)
                if route == "chat":
                    kw["delta"] = ({"content": text} if text or fin is
                                   None else {})
                else:
                    kw["text"] = text
                return protocol.sse(mk(**kw))

            if route == "chat":
                for i in range(parsed.n):  # role preamble per choice
                    w.write(protocol.sse(protocol.chat_chunk(
                        rid=rid, created=created, model=parsed.model,
                        index=i, delta={"role": "assistant",
                                        "content": ""})))
            decoders = [tok.stream_decoder() for _ in range(parsed.n)]
            open_choices = set(range(parsed.n))
            while open_choices:
                try:
                    idx, kind, payload = self._next_item(sub)
                except protocol.ApiError as e:
                    w.write(protocol.sse(e.body()))
                    break
                if kind != "event":
                    continue  # completions close below via finished
                ev = payload
                if ev.error is not None and not ev.finished:
                    # a fault retry in progress: the stream will resume
                    # bit-identically (replay) — surface as an SSE
                    # comment, which clients ignore
                    w.write(f": retrying ({ev.error})\n\n"
                            .encode("utf-8"))
                    continue
                if ev.finished and ev.finish_reason == "error":
                    w.write(protocol.sse(protocol.ApiError(
                        500, ev.error or "generation failed",
                        err_type="server_error",
                        code="generation_error").body()))
                    open_choices.discard(idx)
                    continue
                text = ""
                lp = None
                ids = None
                if ev.token is not None:
                    text = decoders[idx].push(ev.token)
                    if m is not None:
                        m.stream_tokens.inc()
                    if parsed.logprobs:
                        lp = (text, ev.token, ev.logprob or 0.0)
                    if parsed.return_token_ids:
                        ids = [ev.token]
                if ev.finished:
                    text += decoders[idx].flush()
                    fin = protocol.FINISH_REASON_MAP.get(
                        ev.finish_reason, ev.finish_reason)
                    w.write(chunk(idx, text, fin=fin, lp=lp, ids=ids))
                    open_choices.discard(idx)
                elif text or lp is not None or ids is not None:
                    # multi-byte UTF-8 mid-sequence yields no text;
                    # skip the empty frame unless it must carry a
                    # logprob/token-id payload
                    w.write(chunk(idx, text, lp=lp, ids=ids))
            w.write(protocol.SSE_DONE)

    return Handler


def start_api_server(scheduler, tokenizer=None, *, port: int = 0,
                     **kw) -> ApiServer:
    """Construct AND start an :class:`ApiServer` — the one-liner for
    scripts. ``tokenizer`` defaults to a
    :class:`~apex_tpu.serving.api.tokenizer.ByteTokenizer` over the
    engine's vocab::

        server = start_api_server(sched, port=8000,
                                  registry=registry)
    """
    if tokenizer is None:
        tokenizer = ByteTokenizer(scheduler.engine.cfg.vocab_size)
    return ApiServer(scheduler, tokenizer, port=port, **kw).start()
