"""apex_tpu.serving.api — OpenAI-compatible HTTP front end.

The wire layer over the continuous-batching stack: stdlib-only (the
``telemetry/http.py`` discipline — ``http.server`` + ``json`` +
``threading``, nothing else at import), so the ingress tier deploys
anywhere Python runs and the dependency-free test can import it with
jax/numpy purged.

Layout:

- :mod:`~apex_tpu.serving.api.tokenizer` — minimal byte-level text
  codec (token id == UTF-8 byte; streaming-safe incremental decode),
- :mod:`~apex_tpu.serving.api.protocol`  — request parsing/validation
  + response & SSE framing for ``/v1/chat/completions`` and
  ``/v1/completions``,
- :mod:`~apex_tpu.serving.api.constrain` — JSON-schema-constrained
  decoding: a byte-level pushdown automaton whose allowed-byte set
  becomes the sampling step's vocab mask,
- :mod:`~apex_tpu.serving.api.server`    — the threaded HTTP server +
  the single driver thread that owns the scheduler.
"""

from __future__ import annotations

from apex_tpu.serving.api import constrain, protocol, tokenizer  # noqa: F401
from apex_tpu.serving.api.constrain import JsonSchemaConstraint  # noqa: F401
from apex_tpu.serving.api.protocol import (  # noqa: F401
    ApiError,
    render_chat_prompt,
)
from apex_tpu.serving.api.server import (  # noqa: F401
    ApiServer,
    start_api_server,
)
from apex_tpu.serving.api.tokenizer import (  # noqa: F401
    ByteTokenizer,
    StreamDecoder,
)

__all__ = [
    "constrain", "protocol", "server", "tokenizer",
    "ApiServer", "start_api_server", "ApiError", "ByteTokenizer",
    "StreamDecoder", "JsonSchemaConstraint", "render_chat_prompt",
]
