"""Minimal byte-level text codec — the wire front end's tokenizer.

The serving stack is tokenizer-agnostic (requests carry token ids); the
HTTP front end needs SOME text ↔ token mapping to speak OpenAI's
string-in/string-out protocol, and the smallest faithful one is
byte-level: token id ``b`` IS byte ``b`` for ids < 256 (UTF-8), ids >=
256 are reserved for specials (eos) and model-vocab surplus. Because
encoding is per-byte, concatenation distributes over it —
``encode(a + b) == encode(a) + encode(b)`` — which is what makes
host-side stop-STRING matching exactly equal to stop-TOKEN matching,
and what lets the schema-constrained decoder
(:mod:`apex_tpu.serving.api.constrain`) reason about JSON bytes
directly.

Stdlib-only by contract (the api dependency-free test imports this with
jax/numpy purged).
"""

from __future__ import annotations

import codecs
from typing import List, Optional, Sequence


class ByteTokenizer:
    """UTF-8 byte codec over a model vocab: ``encode`` maps text to its
    UTF-8 bytes (each byte one token id), ``decode`` maps ids < 256
    back (invalid UTF-8 → U+FFFD replacement, ids >= 256 skipped —
    they have no byte meaning). Needs ``vocab_size >= 256``."""

    def __init__(self, vocab_size: int,
                 eos_token_id: Optional[int] = None):
        if vocab_size < 256:
            raise ValueError(
                f"byte-level codec needs vocab_size >= 256 (one id per "
                f"byte), got {vocab_size}")
        if eos_token_id is not None \
                and not 0 <= eos_token_id < vocab_size:
            raise ValueError(
                f"eos_token_id {eos_token_id} outside vocab "
                f"[0, {vocab_size})")
        self.vocab_size = vocab_size
        self.eos_token_id = eos_token_id

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, tokens: Sequence[int]) -> str:
        data = bytes(t for t in tokens if 0 <= t < 256)
        return data.decode("utf-8", errors="replace")

    def stream_decoder(self) -> "StreamDecoder":
        return StreamDecoder()


class StreamDecoder:
    """Incremental token → text decoder for SSE streaming: multi-byte
    UTF-8 sequences split across tokens are buffered until complete, so
    every emitted delta is valid text (``push`` may return ``""`` while
    a sequence is pending). ``flush`` drains the tail at end-of-stream
    (an incomplete sequence becomes U+FFFD)."""

    def __init__(self):
        self._dec = codecs.getincrementaldecoder("utf-8")("replace")

    def push(self, token: int) -> str:
        if not 0 <= token < 256:
            return ""  # non-byte id (eos/specials): no text
        return self._dec.decode(bytes([token]))

    def flush(self) -> str:
        return self._dec.decode(b"", final=True)
