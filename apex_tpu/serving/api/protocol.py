"""OpenAI wire types — request parsing/validation + response/SSE
framing, as plain data transforms (no I/O, no engine imports; the
dependency-free test imports this with jax/numpy purged).

Implements the request surface of ``/v1/chat/completions`` and
``/v1/completions`` that maps onto the serving stack: ``messages`` /
``prompt`` (string, or a token-id list — the legacy completions
semantic, handy for tokenizer-less load tools), ``max_tokens``,
``temperature`` / ``top_p`` (+ the ``top_k`` extension), ``n``,
``seed``, ``stream``, ``stop`` (strings; plus the ``stop_token_ids``
extension — lists of token ids, matching the engine's native stop
surface), ``logprobs``, and ``response_format`` (``json_object``, or
``json_schema`` with a schema compiled by
:mod:`apex_tpu.serving.api.constrain`). The ``return_token_ids``
extension echoes raw token ids per choice/chunk — what the bench's
wire-load mode asserts bit-identical against the in-process engine.

Tenant identity: the OpenAI ``user`` field is parsed as the request's
tenant id (the ``X-Tenant-Id`` header, read by the server layer, wins
when both are present) and drives the scheduler's weighted-fair
queueing / per-tenant rate limits; ``model`` routes to a registered
LoRA adapter when it names one (``/v1/models`` lists them) and is
echoed otherwise. Remaining unsupported OpenAI fields pass through
silently; malformed values raise :class:`ApiError` → a 400 with an
OpenAI-shaped error body.

SSE framing: ``data: <json>\\n\\n`` per chunk, ``data: [DONE]\\n\\n``
terminal — exactly what standard OpenAI client libraries parse.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

#: engine finish reason → OpenAI ``finish_reason``
FINISH_REASON_MAP = {
    "eos": "stop",
    "stop": "stop",
    "length": "length",
    "timeout": "timeout",    # non-standard; honest beats lying "length"
    "error": "error",
}

SSE_DONE = b"data: [DONE]\n\n"


class ApiError(Exception):
    """Wire-mappable failure: ``status`` + an OpenAI-shaped error
    body. ``retry_after_s`` (overload) becomes a ``Retry-After``
    header."""

    def __init__(self, status: int, message: str, *,
                 err_type: str = "invalid_request_error",
                 param: Optional[str] = None, code: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.err_type = err_type
        self.param = param
        self.code = code
        self.retry_after_s = retry_after_s

    def body(self) -> Dict[str, Any]:
        err: Dict[str, Any] = {"message": str(self),
                               "type": self.err_type}
        if self.param is not None:
            err["param"] = self.param
        if self.code is not None:
            err["code"] = self.code
        if self.retry_after_s is not None:
            err["retry_after_s"] = round(self.retry_after_s, 3)
        return {"error": err}


def _get(body: Dict[str, Any], key: str, typ, default=None,
         required: bool = False):
    if key not in body or body[key] is None:
        if required:
            raise ApiError(400, f"missing required field {key!r}",
                           param=key)
        return default
    v = body[key]
    if typ is float and isinstance(v, int) and not isinstance(v, bool):
        v = float(v)
    if not isinstance(v, typ) or isinstance(v, bool) and typ is not bool:
        raise ApiError(
            400, f"field {key!r} must be {getattr(typ, '__name__', typ)},"
            f" got {type(v).__name__}", param=key)
    return v


@dataclasses.dataclass
class ParsedRequest:
    """One validated API request, normalized across the two routes.
    ``prompt_text`` is None when the prompt arrived as token ids."""

    model: str
    prompt_text: Optional[str]
    prompt_tokens: Optional[List[int]]
    messages: Optional[List[Dict[str, str]]]
    max_tokens: Optional[int]
    temperature: float
    top_p: float
    top_k: int
    n: int
    seed: Optional[int]
    stream: bool
    stop: List[str]
    stop_token_ids: List[List[int]]
    logprobs: bool
    response_format: Optional[Dict[str, Any]]
    return_token_ids: bool
    echo: bool = False
    #: the OpenAI ``user`` field — tenant identity (the X-Tenant-Id
    #: header wins over it at the server layer); None = anonymous
    user: Optional[str] = None


def render_chat_prompt(messages: Sequence[Dict[str, str]]) -> str:
    """The (deliberately minimal, deterministic) chat template:
    ``role: content`` lines joined by newlines, closed with
    ``assistant:`` — the byte-level codec has no special tokens to
    template with, and the parity oracle needs the rendered prompt to
    be a pure function of the messages."""
    lines = [f"{m['role']}: {m['content']}" for m in messages]
    return "\n".join(lines) + "\nassistant:"


def _parse_common(body: Dict[str, Any]) -> Dict[str, Any]:
    temperature = _get(body, "temperature", float, 0.0)
    top_p = _get(body, "top_p", float, 1.0)
    top_k = _get(body, "top_k", int, 0)
    if temperature < 0.0:
        raise ApiError(400, "temperature must be >= 0",
                       param="temperature")
    if not 0.0 < top_p <= 1.0:
        raise ApiError(400, "top_p must be in (0, 1]", param="top_p")
    if top_k < 0:
        raise ApiError(400, "top_k must be >= 0", param="top_k")
    if (top_k > 0 or top_p < 1.0) and temperature == 0.0:
        raise ApiError(
            400, "top_k/top_p filter sampled draws; set temperature > 0",
            param="temperature")
    n = _get(body, "n", int, 1)
    if not 1 <= n <= 8:
        raise ApiError(400, "n must be in [1, 8]", param="n")
    stop = body.get("stop")
    if stop is None:
        stop = []
    elif isinstance(stop, str):
        stop = [stop]
    elif isinstance(stop, list) and all(
            isinstance(s, str) for s in stop):
        stop = list(stop)
    else:
        raise ApiError(400, "stop must be a string or list of strings",
                       param="stop")
    if len(stop) > 4:
        raise ApiError(400, "at most 4 stop sequences", param="stop")
    stop_ids = body.get("stop_token_ids") or []
    if not (isinstance(stop_ids, list) and all(
            isinstance(s, list) and s and all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in s) for s in stop_ids)):
        raise ApiError(
            400, "stop_token_ids must be a list of non-empty token-id "
            "lists", param="stop_token_ids")
    rf = body.get("response_format")
    if rf is not None:
        if not isinstance(rf, dict) or rf.get("type") not in (
                "text", "json_object", "json_schema"):
            raise ApiError(
                400, "response_format.type must be one of text / "
                "json_object / json_schema", param="response_format")
        if rf.get("type") == "json_schema":
            schema = (rf.get("json_schema") or {}).get("schema")
            if not isinstance(schema, dict):
                raise ApiError(
                    400, "response_format.json_schema.schema must be an "
                    "object", param="response_format")
        bounds = rf.get("bounds")
        if bounds is not None:
            legal = {"max_string_len", "max_int_digits",
                     "max_frac_digits", "max_items", "max_keys",
                     "max_depth"}
            if not isinstance(bounds, dict) or not all(
                    k in legal and isinstance(v, int) and v >= 0
                    for k, v in bounds.items()):
                raise ApiError(
                    400, f"response_format.bounds keys must be from "
                    f"{sorted(legal)} with non-negative int values",
                    param="response_format")
        if rf.get("type") == "text":
            rf = None
    max_tokens = _get(body, "max_tokens", int)
    if max_tokens is not None and max_tokens < 1:
        raise ApiError(400, "max_tokens must be >= 1", param="max_tokens")
    return dict(
        model=_get(body, "model", str, "apex-tpu-gpt"),
        max_tokens=max_tokens,
        temperature=temperature, top_p=top_p, top_k=top_k, n=n,
        seed=_get(body, "seed", int),
        stream=_get(body, "stream", bool, False),
        stop=stop, stop_token_ids=[list(s) for s in stop_ids],
        logprobs=bool(body.get("logprobs") or 0),
        response_format=rf,
        return_token_ids=_get(body, "return_token_ids", bool, False),
        user=_get(body, "user", str),
    )


def parse_chat_request(body: Dict[str, Any]) -> ParsedRequest:
    if not isinstance(body, dict):
        raise ApiError(400, "request body must be a JSON object")
    messages = _get(body, "messages", list, required=True)
    if not messages or not all(
            isinstance(m, dict) and isinstance(m.get("role"), str)
            and isinstance(m.get("content"), str) for m in messages):
        raise ApiError(
            400, "messages must be a non-empty list of {role, content} "
            "objects with string fields", param="messages")
    common = _parse_common(body)
    return ParsedRequest(prompt_text=None, prompt_tokens=None,
                         messages=list(messages), **common)


def parse_completion_request(body: Dict[str, Any]) -> ParsedRequest:
    if not isinstance(body, dict):
        raise ApiError(400, "request body must be a JSON object")
    prompt = body.get("prompt")
    text: Optional[str] = None
    tokens: Optional[List[int]] = None
    if isinstance(prompt, str):
        text = prompt
    elif isinstance(prompt, list) and prompt and all(
            isinstance(t, int) and not isinstance(t, bool)
            for t in prompt):
        tokens = list(prompt)  # legacy token-id prompt
    else:
        raise ApiError(
            400, "prompt must be a string or a non-empty list of token "
            "ids", param="prompt")
    common = _parse_common(body)
    common["echo"] = _get(body, "echo", bool, False)
    return ParsedRequest(prompt_text=text, prompt_tokens=tokens,
                         messages=None, **common)


# -- response building --------------------------------------------------------


def _chat_logprobs(text_tokens: Sequence[Tuple[str, int, float]]
                   ) -> Dict[str, Any]:
    """Chat-format logprobs: one entry per token with its decoded text
    (may be "" inside a multi-byte sequence), byte, and logprob."""
    return {"content": [
        {"token": txt, "logprob": round(lp, 6),
         "bytes": [tok] if 0 <= tok < 256 else [],
         "top_logprobs": []}
        for txt, tok, lp in text_tokens]}


def _completion_logprobs(text_tokens: Sequence[Tuple[str, int, float]]
                         ) -> Dict[str, Any]:
    """Legacy completions-format logprobs."""
    return {
        "tokens": [txt for txt, _, _ in text_tokens],
        "token_logprobs": [round(lp, 6) for _, _, lp in text_tokens],
        "top_logprobs": None,
        "text_offset": [],
    }


def build_chat_response(*, rid: str, created: int, model: str,
                        choices: List[Dict[str, Any]],
                        usage: Dict[str, int]) -> Dict[str, Any]:
    return {"id": rid, "object": "chat.completion", "created": created,
            "model": model, "choices": choices, "usage": usage}


def build_completion_response(*, rid: str, created: int, model: str,
                              choices: List[Dict[str, Any]],
                              usage: Dict[str, int]) -> Dict[str, Any]:
    return {"id": rid, "object": "text_completion", "created": created,
            "model": model, "choices": choices, "usage": usage}


def chat_choice(index: int, text: str, finish_reason: Optional[str],
                *, logprobs: Optional[Dict[str, Any]] = None,
                token_ids: Optional[List[int]] = None) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "index": index,
        "message": {"role": "assistant", "content": text},
        "finish_reason": finish_reason,
        "logprobs": logprobs,
    }
    if token_ids is not None:
        out["token_ids"] = token_ids
    return out


def completion_choice(index: int, text: str,
                      finish_reason: Optional[str], *,
                      logprobs: Optional[Dict[str, Any]] = None,
                      token_ids: Optional[List[int]] = None
                      ) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "index": index, "text": text,
        "finish_reason": finish_reason, "logprobs": logprobs,
    }
    if token_ids is not None:
        out["token_ids"] = token_ids
    return out


def chat_chunk(*, rid: str, created: int, model: str, index: int,
               delta: Dict[str, Any],
               finish_reason: Optional[str] = None,
               logprob: Optional[Tuple[str, int, float]] = None,
               token_ids: Optional[List[int]] = None) -> Dict[str, Any]:
    choice: Dict[str, Any] = {"index": index, "delta": delta,
                              "finish_reason": finish_reason}
    if logprob is not None:
        choice["logprobs"] = _chat_logprobs([logprob])
    if token_ids is not None:
        choice["token_ids"] = token_ids
    return {"id": rid, "object": "chat.completion.chunk",
            "created": created, "model": model, "choices": [choice]}


def completion_chunk(*, rid: str, created: int, model: str, index: int,
                     text: str, finish_reason: Optional[str] = None,
                     logprob: Optional[Tuple[str, int, float]] = None,
                     token_ids: Optional[List[int]] = None
                     ) -> Dict[str, Any]:
    choice: Dict[str, Any] = {"index": index, "text": text,
                              "finish_reason": finish_reason}
    if logprob is not None:
        choice["logprobs"] = _completion_logprobs([logprob])
    if token_ids is not None:
        choice["token_ids"] = token_ids
    return {"id": rid, "object": "text_completion", "created": created,
            "model": model, "choices": [choice]}


def sse(obj: Union[Dict[str, Any], str]) -> bytes:
    """One SSE frame: ``data: <json>\\n\\n``."""
    payload = obj if isinstance(obj, str) else json.dumps(
        obj, separators=(",", ":"))
    return f"data: {payload}\n\n".encode("utf-8")


def usage_dict(prompt_tokens: int, completion_tokens: int
               ) -> Dict[str, int]:
    return {"prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens}
