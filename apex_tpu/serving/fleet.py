"""Fleet router — N engine replicas as ONE unit of reliability.

Everything below the scheduler is already resilient (PR 5: detect /
isolate / recover, the upstream amp loss-scaler loop — ``apex/amp/
scaler.py`` (U)) and observable (PR 10: flight recorder + post-mortem
bundles), but it is one engine in one process: a terminal ``failed``
health state, a guard alarm, or a rolling restart takes the whole
service down. :class:`Router` lifts the same detect → isolate →
recover loop to fleet level over N ``(Engine, Scheduler)`` replicas in
one process (CPU-mesh testable; each replica is its own failure
domain):

- **Health-weighted routing** — ``submit`` places each request on the
  best replica: ``ok`` before ``degraded``, never ``draining`` /
  ``failed`` / breaker-open, weighted by estimated wait (queue depth ×
  the replica's measured chunk-latency EWMA). A per-replica circuit
  breaker driven by the existing watchdog / guard-alarm /
  retry-exhaustion counters takes a misbehaving replica out of
  rotation, fails its work over, rebuilds it, and re-admits it after a
  cooldown.
- **Deterministic failover** — a replica that fails terminally (or
  gives up a request after bounded retries) hands its interrupted work
  to the router through the scheduler's ``on_evict`` hook, each
  request carrying the grow-only emitted-prefix snapshot of everything
  its client already saw. The router resubmits on a healthy replica
  with ``submit(request, replay_prefix=...)``: generation re-derives
  the prefix from the prompt and suppresses the duplicates, so client
  streams stay BIT-IDENTICAL across a replica death — zero duplicate,
  zero lost tokens (every scheduler-visible request is deterministic:
  greedy, or seeded sampling).
- **Drain-for-rolling-restart** — :meth:`Router.drain` takes a replica
  out of rotation, serves its remaining work to completion (the rest
  of the fleet keeps serving — zero downtime), brackets the PR-5
  ``Scheduler.drain()`` machinery, rebuilds the slot buffers
  (``rebuild_slots`` — or a fresh factory replica), and re-admits it:
  the zero-shed restart primitive. :meth:`Router.restart` replaces a
  terminally failed replica from the factory.
- **Fleet overload + observability** — fleet-wide all-or-nothing
  :class:`~apex_tpu.serving.scheduler.QueueFull` whose retry-after
  hint is the BEST replica's ``overload_hint_s()``; aggregated
  ``/healthz`` that answers 200 while ANY replica is ok (degrading
  only when none is); per-replica-labeled fleet metrics
  (``serving_fleet_*``); ``route`` / ``failover`` / ``drain`` /
  ``restart`` flight-recorder events; and a fleet *incident manifest*
  written next to (and linking) the failed replica's own auto-dumped
  post-mortem bundle.

The router duck-types the scheduler surface the API front end drives
(``submit`` / ``step`` / ``pop_events`` / ``completions`` / ``idle`` /
``can_accept`` / ``overload_hint_s`` / ``health`` / ``engine``), so
``ApiServer(router, ...)`` serves a fleet unchanged — 429s become
fleet-aware (all replicas saturated), 503s terminal-fleet-aware (no
replica left standing).

Chaos at fleet scale: build each replica's engine with one plan from a
:class:`~apex_tpu.serving.resilience.FleetFaultPlan` (seeded
``.random``, or ``.kill(i, n)`` for a deterministic
kill-one-replica-mid-burst drill) and the whole soak replays exactly.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

from apex_tpu.serving.request import FINISH_ERROR, Completion, Request, \
    StreamEvent
from apex_tpu.serving.resilience import (
    HEALTH_DEGRADED,
    HEALTH_FAILED,
    HEALTH_OK,
    HEALTH_STATES,
    EngineFailed,
)
from apex_tpu.serving.scheduler import EvictedRequest, QueueFull, Scheduler
from apex_tpu.telemetry import flightrec as flightrec_mod

#: router-level replica states (orthogonal to the per-replica health
#: machine: health says how the ENGINE feels, this says what the
#: ROUTER does with it)
REPLICA_LIVE = "live"          # in rotation
REPLICA_DRAINING = "draining"  # rolling restart: no new routes
REPLICA_COOLING = "cooling"    # breaker open: evicted, counting down
REPLICA_FAILED = "failed"      # terminal; restart(i) replaces it

REPLICA_STATES = (REPLICA_LIVE, REPLICA_DRAINING, REPLICA_COOLING,
                  REPLICA_FAILED)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router policy knobs. The circuit breaker reads the existing
    per-replica resilience counters as DELTAS since it last closed:
    crossing any threshold opens it — the replica's interrupted work
    fails over, its buffers rebuild, and it rejoins rotation after
    ``breaker_cooldown_steps`` router ticks (tick-based, not
    time-based, so chaos soaks with injected clocks stay
    deterministic). ``max_failovers`` bounds how many times one
    request may be failed over before the router completes it with an
    ``error`` outcome (a request that kills every replica it touches
    must not ping-pong forever).

    ``breaker_half_open`` softens the trip: instead of discarding the
    replica's in-flight chunks unfetched, the router lets them finish
    (collects them host-side — no new routes either way) BEFORE
    evicting, so every failed-over snapshot carries the longest
    stream its client saw and fewer tokens re-derive on the healthy
    replicas. Off by default: a watchdog-tripped replica's chunks may
    be the very thing hanging, and the hard trip must stay the safe
    floor."""

    breaker_watchdog_trips: int = 2
    breaker_guard_alarms: int = 1
    breaker_retry_exhausted: int = 2
    breaker_cooldown_steps: int = 50
    breaker_half_open: bool = False
    max_failovers: int = 2
    drain_max_steps: int = 100_000

    def __post_init__(self):
        for f in ("breaker_watchdog_trips", "breaker_guard_alarms",
                  "breaker_retry_exhausted"):
            if getattr(self, f) < 1:
                raise ValueError(f"{f} must be >= 1 (the breaker must "
                                 f"tolerate zero signals)")
        if self.breaker_cooldown_steps < 1:
            raise ValueError("breaker_cooldown_steps must be >= 1")
        if self.max_failovers < 1:
            raise ValueError("max_failovers must be >= 1")


class _Pending:
    """One evicted request awaiting placement: the request, the
    emitted prefix its client already saw, the replica it came from
    (excluded from re-placement while any other candidate exists), and
    how many times it has failed over already."""

    __slots__ = ("request", "tokens", "logprobs", "source", "failovers")

    def __init__(self, request: Request, tokens: List[int],
                 logprobs: List[float], source: int, failovers: int):
        self.request = request
        self.tokens = tokens
        self.logprobs = logprobs
        self.source = source
        self.failovers = failovers


class _Replica:
    """Router-side bookkeeping for one ``(Engine, Scheduler)`` pair."""

    __slots__ = ("index", "sched", "state", "cooldown", "routed",
                 "base_watchdog", "base_guard", "base_exhausted",
                 "evicted_ids", "evict_cause")

    def __init__(self, index: int, sched: Scheduler):
        self.index = index
        self.sched = sched
        self.state = REPLICA_LIVE
        self.cooldown = 0
        self.routed = 0
        #: ids + cause of the most recent eviction wave — the incident
        #: manifest's evidence
        self.evicted_ids: List[str] = []
        self.evict_cause: Optional[str] = None
        self.reset_breaker()

    def reset_breaker(self) -> None:
        """Re-baseline the breaker deltas at the current counters —
        called when the breaker closes (cooldown over, drain cycle
        done) so old incidents never re-trip it."""
        s = self.sched
        self.base_watchdog = s._watchdog_trips
        self.base_exhausted = s._retry_exhausted
        self.base_guard = s._guard_alarm_count()

    def breaker_cause(self, cfg: FleetConfig) -> Optional[str]:
        """Which breaker threshold (if any) the counter deltas since
        the last close have crossed."""
        s = self.sched
        if s._watchdog_trips - self.base_watchdog \
                >= cfg.breaker_watchdog_trips:
            return "watchdog"
        if s._guard_alarm_count() - self.base_guard \
                >= cfg.breaker_guard_alarms:
            return "guard_alarm"
        if s._retry_exhausted - self.base_exhausted \
                >= cfg.breaker_retry_exhausted:
            return "retry_exhausted"
        return None

    @property
    def health_state(self) -> str:
        return self.sched.health.state

    def routable(self) -> bool:
        return (self.state == REPLICA_LIVE
                and self.health_state in (HEALTH_OK, HEALTH_DEGRADED))


class _FleetMetrics:
    """Pre-bound fleet-registry handles (one labels() resolution here,
    none on the routing hot path) — the per-replica-labeled surface
    dashboards watch a fleet through."""

    def __init__(self, registry, n: int):
        registry.gauge(
            "serving_fleet_replicas", "engine replicas owned by the "
            "router (any state)").set(n)
        self.routable = registry.gauge(
            "serving_fleet_replicas_routable",
            "replicas currently accepting routed submits")
        h = registry.gauge(
            "serving_fleet_replica_health",
            "per-replica health: 0=ok 1=degraded 2=draining 3=failed",
            labels=("replica",))
        self.health = {i: h.labels(replica=str(i)) for i in range(n)}
        b = registry.gauge(
            "serving_fleet_breaker_open",
            "per-replica circuit breaker: 1 while the replica is out "
            "of rotation (cooling/draining/failed), 0 in rotation",
            labels=("replica",))
        self.breaker = {i: b.labels(replica=str(i)) for i in range(n)}
        r = registry.counter(
            "serving_fleet_routed_total",
            "requests placed on a replica by the router",
            labels=("replica",))
        self.routed = {i: r.labels(replica=str(i)) for i in range(n)}
        p = registry.gauge(
            "serving_fleet_predicted_ttft_seconds",
            "per-replica predicted time-to-first-token for the NEXT "
            "submit (queue depth x chunk-latency EWMA + sketch-backed "
            "admission overhead) — the SLO-aware routing signal "
            "precursor",
            labels=("replica",))
        self.predicted_ttft = {i: p.labels(replica=str(i))
                               for i in range(n)}
        self.failovers = registry.counter(
            "serving_fleet_failovers_total",
            "eviction waves failed over (replica deaths, breaker "
            "trips, per-request retry exhaustion hand-offs)")
        self.failed_over = registry.counter(
            "serving_fleet_failed_over_requests_total",
            "requests resubmitted to another replica with their "
            "emitted-prefix snapshot")
        self.drains = registry.counter(
            "serving_fleet_drains_total",
            "drain -> rebuild -> re-admit rolling-restart cycles "
            "completed")
        self.queue_full = registry.counter(
            "serving_fleet_queue_full_total",
            "fleet-wide submit rejections (no replica could accept)")


class FleetHealth:
    """The fleet-aggregated health view: the best replica wins. 200
    while ANY replica is ``ok`` or ``degraded`` (the fleet is
    serving), 503 only when none is — a load balancer in front of the
    router keeps sending traffic as long as one replica can take it.
    Duck-types the per-engine ``HealthMonitor`` surface the API server
    and ``MetricsServer(health=...)`` read (``state`` / ``code`` /
    ``healthz``)."""

    def __init__(self, router: "Router"):
        self._router = router

    @property
    def state(self) -> str:
        states = [r.health_state for r in self._router.replicas]
        for s in (HEALTH_OK, HEALTH_DEGRADED):
            if s in states:
                return s
        return ("draining" if "draining" in states else HEALTH_FAILED)

    @property
    def code(self) -> int:
        return HEALTH_STATES.index(self.state)

    @property
    def last_cause(self) -> Optional[str]:
        causes = [r.sched.health.last_cause
                  for r in self._router.replicas]
        return next((c for c in causes if c), None)

    def healthz(self) -> Tuple[int, str]:
        state = self.state
        status = 200 if state in (HEALTH_OK, HEALTH_DEGRADED) else 503
        per = " ".join(f"r{r.index}={r.health_state}"
                       for r in self._router.replicas)
        return status, f"{state} ({per})\n"


class Router:
    """Own N replicas; route, fail over, drain, restart.

    >>> scheds = [Scheduler(Engine(cfg, params, mesh, ecfg).warmup())
    ...           for _ in range(2)]
    >>> with Router(scheds) as router:
    ...     router.submit(Request("r0", prompt, max_tokens=16))
    ...     router.run_until_idle()
    ...     router.completions["r0"].tokens

    Every scheduler must be exclusively owned (the router installs its
    ``on_evict`` hook) over a warmed engine of IDENTICAL model/engine
    config — any replica must be able to serve any request, and
    failover determinism rests on identical compiled programs.
    ``factory(i) -> Scheduler`` (optional) builds replacement replicas
    for :meth:`restart` and ``drain(i, replace=True)``.

    ``registry`` receives the fleet-level metrics (give each replica
    its OWN registry if you also want per-replica scrapes — the
    unlabeled per-engine names would collide in a shared one);
    ``recorder`` logs ``route``/``failover``/``drain``/``restart``
    decisions; ``bundle_dir`` is where fleet incident manifests land,
    next to (and linking) the replicas' own post-mortem bundles.

    ONE thread drives the router (``step``/``run_until_idle``/
    ``drain``/``restart``), exactly like a scheduler — the ApiServer's
    driver thread, or your loop, never both at once.
    """

    def __init__(self, schedulers: Sequence[Scheduler], *,
                 factory: Optional[Callable[[int], Scheduler]] = None,
                 config: Optional[FleetConfig] = None,
                 registry=None, recorder=None,
                 bundle_dir: Optional[str] = None,
                 tenancy=None,
                 clock: Callable[[], float] = time.monotonic):
        scheds = list(schedulers)
        if not scheds:
            raise ValueError("a fleet needs at least one replica")
        if len({id(s) for s in scheds}) != len(scheds) or \
                len({id(s.engine) for s in scheds}) != len(scheds):
            raise ValueError(
                "replicas must be distinct (Engine, Scheduler) pairs — "
                "two routes into one engine would double-admit")
        for s in scheds:
            self._check_compatible(scheds[0], s)
            if s.on_evict is not None:
                raise ValueError(
                    "scheduler already has an on_evict owner — a "
                    "replica belongs to exactly one router")
            if s.health.state == HEALTH_FAILED:
                raise ValueError(
                    "cannot adopt a terminally failed scheduler")
        self.cfg = config or FleetConfig()
        self.factory = factory
        self.clock = clock
        self.recorder = recorder
        self.bundle_dir = bundle_dir
        #: fleet incident manifests written so far (paths, oldest
        #: first) — one per terminal replica failure
        self.incidents_written: List[str] = []
        self._incident_counter = 0
        self.telemetry = (None if registry is None
                          else _FleetMetrics(registry, len(scheds)))
        self._registry = registry
        self.replicas: List[_Replica] = []
        for i, s in enumerate(scheds):
            rep = _Replica(i, s)
            s.on_evict = self._evict_hook(rep)
            self.replicas.append(rep)
        #: merged client-facing surfaces — the router harvests every
        #: replica's events/completions each step, so these are the
        #: ONE place callers read (replica-level maps stay empty)
        self.events: Deque[StreamEvent] = collections.deque()
        self.completions: Dict[str, Completion] = {}
        self.health = FleetHealth(self)
        self._pending: Deque[_Pending] = collections.deque()
        self._failover_counts: Dict[str, int] = {}
        #: tenant → last replica index that served it (the affinity
        #: HINT: a warm-cache tiebreak in routing, never a constraint)
        self._tenant_affinity: Dict[str, int] = {}
        #: fleet-level tenant rate limiting (serving.tenancy): a
        #: tenant's token budget is a FLEET property — per-replica
        #: buckets would multiply the effective cap by the replica
        #: count and 429 one replica while others sat full — so rate
        #: limits belong HERE, at ingress, with ONE bucket per tenant.
        #: Pass a TenancyConfig with `rates` to the Router and leave
        #: the replica schedulers' tenancy rate-free (their WFQ
        #: weights still apply per replica). Failover re-placements
        #: bypass it the same way the scheduler-level bucket does —
        #: the original submit already charged the budget.
        self._tenant_book = None
        if tenancy is not None:
            from apex_tpu.serving.tenancy import TenantBook

            self._tenant_book = TenantBook(tenancy, clock)
        #: fleet-level adapter registrations, replayed onto factory
        #: replacements so ids mean the same weights fleet-wide
        self._adapter_registrations: List[Dict[str, Any]] = []
        self._steps = 0
        self._routed = 0
        self._failover_waves = 0
        self._failed_over_requests = 0
        self._aborted_requests = 0
        self._drains = 0
        self._restarts = 0
        self._queue_full = 0
        self._update_gauges()

    @staticmethod
    def _check_compatible(a: Scheduler, b: Scheduler) -> None:
        ea, eb = a.engine, b.engine
        same = (ea.cfg.vocab_size == eb.cfg.vocab_size
                and ea.engine_cfg.max_prompt_len
                == eb.engine_cfg.max_prompt_len
                and ea.engine_cfg.max_seq_len == eb.engine_cfg.max_seq_len
                and ea.engine_cfg.decode_chunk
                == eb.engine_cfg.decode_chunk
                and ea.engine_cfg.spec_k == eb.engine_cfg.spec_k
                and ea.engine_cfg.adapter_slots
                == eb.engine_cfg.adapter_slots
                and ea.engine_cfg.adapter_rank
                == eb.engine_cfg.adapter_rank
                and ea.engine_cfg.adapter_alpha
                == eb.engine_cfg.adapter_alpha)
        if not same:
            raise ValueError(
                "replica engine configs differ (vocab / prompt room / "
                "seq len / decode_chunk / spec_k / adapter pool) — "
                "any replica must be able to serve any request, and "
                "failover streams must be bit-identical across "
                "replicas")

    # -- intake -------------------------------------------------------------

    def submit(self, request: Request) -> None:
        """Route ``request`` to the best replica (health tier, then
        estimated wait, then index — deterministic). Raises
        :class:`~apex_tpu.serving.scheduler.QueueFull` when NO replica
        can take it right now (retry-after = the best replica's drain
        estimate) and
        :class:`~apex_tpu.serving.resilience.EngineFailed` only when
        the whole fleet is terminally failed. Request-validity errors
        raise unchanged."""
        rid = request.request_id
        if rid in self.completions or any(
                p.request.request_id == rid for p in self._pending) \
                or any(rid in rep.sched._req_records
                       for rep in self.replicas):
            raise ValueError(f"duplicate request_id {rid!r}")
        book = self._tenant_book
        if book is not None:
            from apex_tpu.serving.tenancy import TenantThrottled

            tenant = request.tenant = book.admit_tenant(
                request.tenant or "default")
            wait = book.throttle(tenant, request.max_tokens)
            if wait is not None:
                book.stats(tenant).throttled += 1
                book.stats(tenant).shed += 1
                if self.recorder is not None:
                    self.recorder.record("tenant_throttle", rid,
                                         tenant, wait)
                raise TenantThrottled(
                    f"tenant {tenant!r} over its fleet token budget; "
                    f"retry in ~{wait:.3f}s", tenant=tenant,
                    retry_after_s=wait)
        self._route(request, None, None, exclude=None, fresh=True)

    def can_accept(self, n: int = 1) -> bool:
        """Fleet pre-flight for an all-or-nothing batch: can the
        routable replicas absorb ``n`` submissions between them?"""
        room = 0
        for rep in self.replicas:
            if rep.routable():
                room += max(rep.sched.max_queue
                            - len(rep.sched.queue), 0)
                if room >= n:
                    return True
        return False

    def overload_hint_s(self) -> float:
        """The BEST routable replica's queue-drain estimate — what a
        fleet-wide 429's Retry-After should say (the next request goes
        to that replica)."""
        hints = [rep.sched.overload_hint_s()
                 for rep in self.replicas if rep.routable()]
        return min(hints) if hints else 0.0

    def _candidates(self, exclude: Optional[int],
                    tenant: Optional[str] = None) -> List[_Replica]:
        reps = [r for r in self.replicas
                if r.routable() and r.index != exclude]
        if not reps and exclude is not None:
            # the excluded source is the only replica left standing —
            # better the same replica than an error outcome
            reps = [r for r in self.replicas if r.routable()]
        # tenant affinity is a HINT, deliberately the weakest key:
        # among replicas tied on health AND load, prefer the one that
        # last served this tenant (its adapter gathers / prefix pages
        # are warm there) — never at the cost of routing onto a
        # sicker or busier replica, so fairness and failover
        # determinism are untouched
        sticky = (self._tenant_affinity.get(tenant)
                  if tenant is not None else None)
        # parked conversations and queued resumes are LATENT load: a
        # host-swap replica's idle slots are spoken for by streams
        # that will swap back in, so the occupancy key counts them —
        # routing spreads new arrivals away from oversubscribed
        # replicas before their resumes reclaim the pages
        return sorted(reps, key=lambda r: (
            0 if r.health_state == HEALTH_OK else 1,
            r.sched.overload_hint_s(),
            len(r.sched.queue) + len(r.sched.active)
            + len(r.sched._parked) + len(r.sched._resume_q),
            0 if r.index == sticky else 1,
            r.index))

    def _route(self, request: Request, tokens: Optional[List[int]],
               logprobs: Optional[List[float]], *,
               exclude: Optional[int], fresh: bool) -> bool:
        """Place one request (fresh submit, or a failover with its
        emitted prefix). Fresh submits raise on fleet saturation;
        failovers return False and stay pending."""
        candidates = self._candidates(exclude,
                                      getattr(request, "tenant", None))
        if not candidates:
            if all(r.state == REPLICA_FAILED or
                   r.health_state == HEALTH_FAILED
                   for r in self.replicas):
                if fresh:
                    raise EngineFailed(
                        "every fleet replica is terminally failed; "
                        "not accepting requests")
                return False
            if fresh:
                self._note_queue_full(request, 0)
                raise QueueFull(
                    "no replica in rotation (draining/cooling); retry "
                    "shortly", queue_depth=0,
                    retry_after_s=self.overload_hint_s())
            return False
        depth = 0
        for rep in candidates:
            try:
                rep.sched.submit(request, replay_prefix=tokens,
                                 replay_logprobs=logprobs)
            except QueueFull as e:
                depth = max(depth, e.queue_depth)
                continue
            except EngineFailed:
                continue  # lost a race with a terminal transition
            rep.routed += 1
            self._routed += 1
            tenant = getattr(request, "tenant", None)
            if tenant:
                self._tenant_affinity[tenant] = rep.index
            if self.recorder is not None:
                self.recorder.record(
                    "route", request.request_id, rep.index,
                    rep.health_state, rep.sched.overload_hint_s())
            if self.telemetry is not None:
                self.telemetry.routed[rep.index].inc()
            return True
        if fresh:
            self._note_queue_full(request, depth)
            raise QueueFull(
                f"every routable replica is at capacity "
                f"({len(candidates)} tried)", queue_depth=depth,
                retry_after_s=self.overload_hint_s())
        return False

    def _note_queue_full(self, request: Request, depth: int) -> None:
        self._queue_full += 1
        if self.recorder is not None:
            self.recorder.record("queue_full", request.request_id,
                                 depth, False)
        if self.telemetry is not None:
            self.telemetry.queue_full.inc()

    # -- failover ------------------------------------------------------------

    def _evict_hook(self, rep: _Replica):
        def hook(evicted: List[EvictedRequest], cause: str) -> None:
            self._on_evict(rep, evicted, cause)
        return hook

    def _on_evict(self, rep: _Replica, evicted: List[EvictedRequest],
                  cause: str) -> None:
        """A replica handed over interrupted work (terminal failure,
        breaker eviction, or one retry-exhausted request): queue it
        for placement on a healthy replica. Runs inside the failing
        scheduler's tick — placement happens in :meth:`step`, never
        re-entrantly."""
        self._failover_waves += 1
        rep.evict_cause = cause
        rep.evicted_ids = [e.request.request_id for e in evicted]
        if self.recorder is not None:
            self.recorder.record("failover", rep.index, cause,
                                 len(evicted))
        if self.telemetry is not None:
            self.telemetry.failovers.inc()
        for e in evicted:
            n = self._failover_counts.get(e.request.request_id, 0) + 1
            self._failover_counts[e.request.request_id] = n
            self._pending.append(_Pending(
                e.request, e.tokens, e.logprobs, rep.index, n))

    def _place_pending(self) -> None:
        if not self._pending:
            return
        still: Deque[_Pending] = collections.deque()
        any_routable = any(r.routable() for r in self.replicas)
        while self._pending:
            p = self._pending.popleft()
            if p.failovers > self.cfg.max_failovers:
                self._abort(p, f"{p.failovers - 1} failovers exhausted")
                continue
            if not any_routable:
                if all(r.state == REPLICA_FAILED
                       or r.health_state == HEALTH_FAILED
                       for r in self.replicas):
                    self._abort(p, "every replica terminally failed")
                else:
                    still.append(p)  # a drain/cooldown will end
                continue
            try:
                placed = self._route(p.request, p.tokens, p.logprobs,
                                     exclude=p.source, fresh=False)
            except ValueError as e:
                self._abort(p, f"failover resubmit rejected: {e}")
                continue
            if placed:
                self._failed_over_requests += 1
                if self.telemetry is not None:
                    self.telemetry.failed_over.inc()
            else:
                still.append(p)
        self._pending = still

    def _abort(self, p: _Pending, cause: str) -> None:
        """Terminal router-level outcome: the fleet could not serve
        this request anywhere — one ``error`` event + a completion
        carrying the longest stream the client saw (the single-engine
        exhaustion semantics, at fleet scope)."""
        self._aborted_requests += 1
        self._failover_counts.pop(p.request.request_id, None)
        arrival = p.request.arrival_time
        latency = (max(self.clock() - arrival, 0.0)
                   if arrival is not None else 0.0)
        self.events.append(StreamEvent(
            p.request.request_id, None, True, FINISH_ERROR,
            error=cause))
        self.completions[p.request.request_id] = Completion(
            p.request.request_id, list(p.tokens), FINISH_ERROR,
            ttft=None, latency=latency, logprobs=list(p.logprobs))

    # -- the loop ------------------------------------------------------------

    def step(self) -> None:
        """One fleet tick: tick every non-failed replica, scan for
        terminal failures and breaker trips (evict + rebuild + cool),
        harvest events/completions into the merged surfaces, place
        pending failovers."""
        self._steps += 1
        for rep in self.replicas:
            if rep.state != REPLICA_FAILED:
                rep.sched.step()
        self._scan()
        self._harvest()
        self._place_pending()
        self._update_gauges()

    def _scan(self) -> None:
        for rep in self.replicas:
            if rep.state == REPLICA_FAILED:
                continue
            if rep.health_state == HEALTH_FAILED:
                # the scheduler's terminal transition already evicted
                # its work through the hook; record the incident and
                # take the replica out of the fleet
                rep.state = REPLICA_FAILED
                self._write_incident(rep, rep.sched.health.last_cause
                                     or "failed")
                continue
            if rep.state == REPLICA_COOLING:
                rep.cooldown -= 1
                if rep.cooldown <= 0:
                    rep.reset_breaker()
                    rep.state = REPLICA_LIVE
                    if self.recorder is not None:
                        self.recorder.record("drain", rep.index,
                                             "readmit")
                continue
            if rep.state != REPLICA_LIVE:
                continue
            cause = rep.breaker_cause(self.cfg)
            if cause is not None:
                self._trip_breaker(rep, cause)

    def _trip_breaker(self, rep: _Replica, cause: str) -> None:
        """Open the replica's circuit: evict its current work to the
        healthy replicas, rebuild its buffers, and cool it down out of
        rotation. The health machine stays whatever it was — the
        breaker is ROUTER policy layered on top.

        Half-open mode (``FleetConfig.breaker_half_open``) first
        collects the replica's in-flight chunks so their tokens land
        in the eviction snapshots instead of being discarded unfetched
        — the failed-over streams re-derive less on arrival. A seam
        fault during that collection recovers through the scheduler's
        own machinery (snapshots grow either way); the eviction below
        proceeds regardless."""
        if self.cfg.breaker_half_open:
            try:
                while rep.sched._inflight:
                    rep.sched._collect_oldest()
            except Exception:  # collection died with the replica —
                pass           # eject what the snapshots already hold
        rep.sched.eject_all(f"breaker ({cause})")
        rep.sched.engine.rebuild_slots()
        rep.state = REPLICA_COOLING
        rep.cooldown = self.cfg.breaker_cooldown_steps

    def _harvest(self) -> None:
        for rep in self.replicas:
            sched = rep.sched
            evs = sched.pop_events()
            if evs:
                self.events.extend(evs)
            if sched.completions:
                for rid in list(sched.completions):
                    self.completions[rid] = sched.completions.pop(rid)
                    self._failover_counts.pop(rid, None)

    def pop_events(self) -> List[StreamEvent]:
        """Drain the merged response stream."""
        out = list(self.events)
        self.events.clear()
        return out

    def idle(self) -> bool:
        """Nothing to do — no pending failovers, every non-failed
        replica idle, AND no breaker cooldown counting down: the
        cooldown is tick-based, so a cooling replica is pending work
        (an idle-gated driver that stopped ticking would otherwise
        strand it out of rotation forever — with an all-cooling fleet
        429ing every submit that could have re-admitted it)."""
        if self._pending:
            return False
        return all(rep.state == REPLICA_FAILED
                   or (rep.state != REPLICA_COOLING and rep.sched.idle())
                   for rep in self.replicas)

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        """Step until every replica and the failover queue are empty
        (offline batch mode). Sleeps out retry-backoff gates exactly
        like the single-replica loop."""
        steps = 0
        while not self.idle():
            self.step()
            steps += 1
            if steps > max_steps:
                busy = [r.index for r in self.replicas
                        if r.state != REPLICA_FAILED
                        and not r.sched.idle()]
                raise RuntimeError(
                    f"fleet not idle after {max_steps} steps — busy "
                    f"replicas {busy}, {len(self._pending)} failovers "
                    f"pending")
            self._maybe_sleep()

    def _maybe_sleep(self) -> None:
        """When backoff gates are the ONLY remaining fleet work, wait
        the earliest one out through the first gated replica's
        injected ``sleep`` instead of spinning ticks."""
        if self._pending:
            return
        waits = []
        sleeper = None
        for rep in self.replicas:
            if rep.state == REPLICA_FAILED or rep.sched.idle():
                continue
            w = rep.sched._backoff_wait_s()
            if w is None:
                return  # this replica can make real progress now
            waits.append(w)
            sleeper = sleeper or rep.sched
        if waits and sleeper is not None:
            sleeper.sleep(min(waits))

    # -- rolling restart -----------------------------------------------------

    def drain(self, index: int, *, replace: bool = False) -> None:
        """Zero-downtime rolling restart of replica ``index``: take it
        out of rotation, serve its remaining queued + active work to
        completion (the rest of the fleet keeps serving — this call
        drives fleet ticks), bracket the PR-5 pipeline drain (a
        replica-level ``/healthz`` probe reads ``draining``), rebuild
        the slot buffers — or build a fresh factory replica with
        ``replace=True`` — and re-admit it to rotation. Zero requests
        are shed or errored by the cycle.

        Threading: this call DRIVES fleet ticks, so it must run on the
        thread that owns the router's step loop — the router inherits
        the scheduler's single-driver-thread discipline. Under a live
        ``ApiServer`` (whose driver thread owns the stepping), run the
        drain through that thread (stop the server, or hand it a
        closure to execute between ticks); calling it from another
        thread would race two drivers over the same schedulers."""
        rep = self._replica(index)
        if rep.state == REPLICA_FAILED:
            raise ValueError(
                f"replica {index} is terminally failed — use "
                f"restart({index})")
        if self.recorder is not None:
            self.recorder.record("drain", index, "begin")
        rep.state = REPLICA_DRAINING
        steps = 0
        while not rep.sched.idle():
            self.step()
            steps += 1
            if steps > self.cfg.drain_max_steps:
                raise RuntimeError(
                    f"replica {index} not idle after {steps} drain "
                    f"steps")
            if rep.state == REPLICA_FAILED:
                raise EngineFailed(
                    f"replica {index} failed terminally mid-drain "
                    f"({rep.sched.health.last_cause}); its work was "
                    f"failed over — restart({index}) replaces it")
            self._maybe_sleep()
        rep.sched.drain()   # the PR-5 bracket: draining observed
        if self.recorder is not None:
            self.recorder.record("drain", index, "idle")
        if replace:
            self._replace(rep, "drain")
        else:
            rep.sched.engine.rebuild_slots()
        if self.recorder is not None:
            self.recorder.record("drain", index, "rebuilt")
        rep.reset_breaker()
        rep.cooldown = 0
        rep.state = REPLICA_LIVE
        self._drains += 1
        if self.recorder is not None:
            self.recorder.record("drain", index, "readmit")
        if self.telemetry is not None:
            self.telemetry.drains.inc()
        self._update_gauges()

    def restart(self, index: int,
                journal_dir: Optional[str] = None) -> None:
        """Replace a terminally failed replica from the factory and
        re-admit it to rotation (its interrupted work already failed
        over when it died).

        ``journal_dir`` points at the dead replica's write-ahead
        journal (``apex_tpu.serving.journal``): the replacement
        replays its unfinished state — pooled prefixes and every
        request the eviction hook never got to hand over (a SIGKILL'd
        process evicts nothing), with their emitted prefixes intact —
        so a whole-process replica death recovers instead of dropping
        streams. Work that DID fail over was journaled finished
        ("evicted") by the dying scheduler and is never resubmitted
        twice; adapters re-register through the fleet's own ledger
        either way, keeping ids aligned across siblings."""
        rep = self._replica(index)
        if rep.state != REPLICA_FAILED:
            raise ValueError(
                f"replica {index} is {rep.state}, not failed — use "
                f"drain({index}) for a rolling restart")
        self._replace(rep, "failed")
        if journal_dir is not None:
            from apex_tpu.serving import journal as journal_mod
            journal_mod.replay_into(rep.sched, journal_dir)
        rep.reset_breaker()
        rep.cooldown = 0
        rep.state = REPLICA_LIVE
        self._restarts += 1
        if self.recorder is not None:
            self.recorder.record("restart", index,
                                 rep.evict_cause or "failed")
        self._update_gauges()

    def _replace(self, rep: _Replica, why: str) -> None:
        if self.factory is None:
            raise ValueError(
                f"no replica factory: Router(factory=...) is required "
                f"to replace replica {rep.index} ({why})")
        sched = self.factory(rep.index)
        self._check_compatible(self.replicas[0].sched, sched)
        if sched.on_evict is not None:
            raise ValueError("factory scheduler already has an "
                             "on_evict owner")
        sched.engine.warmup()   # idempotent; a cold replacement must
        # never recompile mid-rotation under the fleet's armed guards
        for kw in self._adapter_registrations:
            # a replacement replica must serve every registered
            # adapter at the SAME ids as its siblings, or a tenant's
            # failed-over stream would decode on the wrong weights
            sched.register_adapter(**kw)
        old = rep.sched
        rep.sched = sched
        sched.on_evict = self._evict_hook(rep)
        old.on_evict = None
        old.engine.close()

    def _replica(self, index: int) -> _Replica:
        if not 0 <= index < len(self.replicas):
            raise ValueError(
                f"replica {index} outside fleet "
                f"[0, {len(self.replicas)})")
        return self.replicas[index]

    # -- incidents -----------------------------------------------------------

    def _write_incident(self, rep: _Replica, cause: str) -> None:
        """One terminal replica failure = one fleet incident manifest:
        an atomic bundle directory linking the replica's own
        auto-dumped post-mortem bundles to the fleet-level picture
        (what was evicted, where the fleet stood). Disk errors are
        swallowed — losing the manifest must never take down the
        routing loop that survived the replica."""
        if self.bundle_dir is None:
            return
        manifest = {
            "incident_version": 1,
            "kind": "fleet_incident",
            "cause": cause,
            "replica": rep.index,
            "wall_time": time.time(),
            "evicted_request_ids": list(rep.evicted_ids),
            "replica_bundles": list(rep.sched.bundles_written),
            "replica_health": {
                "state": rep.health_state,
                "last_cause": rep.sched.health.last_cause,
            },
            "fleet": self.summary(),
        }
        while True:
            name = (f"fleet-incident-{self._incident_counter:04d}"
                    f"-r{rep.index}")
            path = os.path.join(self.bundle_dir, name)
            self._incident_counter += 1
            if not os.path.exists(path):
                break
        try:
            path = flightrec_mod.write_bundle(
                path, {"manifest.json": manifest})
        except OSError:
            return
        self.incidents_written.append(path)
        if self.recorder is not None:
            self.recorder.record("bundle", f"fleet-{cause}",
                                 os.path.basename(path))

    # -- shared-engine conveniences ------------------------------------------

    @property
    def engine(self):
        """Replica 0's engine — the config surface API layers read
        (every replica's model/engine config is identical by
        construction). Use :meth:`register_prefix` (not
        ``router.engine.register_prefix``) to register templates, so
        EVERY replica serves the hit."""
        return self.replicas[0].sched.engine

    def register_prefix(self, tokens) -> List[int]:
        """Register a shared-prompt template into EVERY replica's
        prefix pool (after warmup) — failover keeps streams
        bit-identical either way (prefix-hit == cold is an oracle),
        but only a fleet-wide registration keeps the admission
        SPEEDUP after a request moves replicas."""
        return [rep.sched.engine.register_prefix(tokens)
                for rep in self.replicas]

    def register_adapter(self, weights=None, *, name=None,
                         seed=None) -> List[int]:
        """Register a LoRA adapter into EVERY replica's pool (after
        warmup) — registration order is identical across replicas by
        construction, so a tenant's adapter id means the same weights
        everywhere and failover streams stay bit-identical. Recorded
        fleet-side too: a factory replacement replays the sequence."""
        self._adapter_registrations.append(
            {"weights": weights, "name": name, "seed": seed})
        return [rep.sched.register_adapter(weights, name=name,
                                           seed=seed)
                for rep in self.replicas]

    # -- reporting -----------------------------------------------------------

    def _update_gauges(self) -> None:
        tele = self.telemetry
        if tele is None:
            return
        tele.routable.set(sum(r.routable() for r in self.replicas))
        for rep in self.replicas:
            g = tele.health.get(rep.index)
            if g is not None:
                g.set(HEALTH_STATES.index(rep.health_state))
            b = tele.breaker.get(rep.index)
            if b is not None:
                b.set(0.0 if rep.state == REPLICA_LIVE else 1.0)
            p = tele.predicted_ttft.get(rep.index)
            if p is not None:
                p.set(rep.sched.predicted_ttft_s())

    def fleet_sketch(self, metric: str):
        """Merge every replica's quantile sketch for ``metric`` into
        one fleet sketch (merge works on COPIES — a replica's live
        sketch is never mutated by reporting). DDSketch merge is exact
        bucket addition, so the fleet percentile equals the percentile
        of the pooled samples within the configured relative error —
        NOT an average of per-replica percentiles, which would be
        meaningless. None when no replica runs an SLO monitor (or all
        sketches are empty)."""
        merged = None
        for rep in self.replicas:
            mon = rep.sched.slo
            if mon is None:
                continue
            sk = mon.sketch(metric)
            if sk is None or not sk.count:
                continue
            merged = sk.copy() if merged is None else merged.merge(
                sk.copy())
        return merged

    def fleet_percentiles(self, metric: str) -> Dict[str, float]:
        """Fleet-pooled ``{count, p50_ms, p95_ms, p99_ms}`` for one
        SLO metric — empty dict when nothing is recorded yet."""
        sk = self.fleet_sketch(metric)
        if sk is None:
            return {}
        return {"count": float(sk.count),
                "p50_ms": sk.quantile(0.50) * 1e3,
                "p95_ms": sk.quantile(0.95) * 1e3,
                "p99_ms": sk.quantile(0.99) * 1e3}

    def slo_status(self) -> Optional[Dict[str, Any]]:
        """The fleet ``/slo`` aggregate: per-replica monitor status
        plus fleet-merged percentiles and the worst objective state
        across the fleet. None when no replica runs a monitor (the
        route 404s, matching the single-scheduler contract)."""
        from apex_tpu.telemetry.slo import METRICS as SLO_METRICS
        per_replica = {
            str(rep.index): rep.sched.slo.status()
            for rep in self.replicas if rep.sched.slo is not None}
        if not per_replica:
            return None
        order = ("ok", "warning", "burning")
        worst = max((s["state"] for s in per_replica.values()),
                    key=order.index)
        return {
            "state": worst,
            "fleet": {m: self.fleet_percentiles(m)
                      for m in SLO_METRICS},
            "replicas": per_replica,
            "predicted_ttft_s": {
                str(rep.index): rep.sched.predicted_ttft_s()
                for rep in self.replicas},
        }

    def summary(self) -> Dict[str, float]:
        """Fleet-level aggregate (flat floats, like
        ``Scheduler.summary()`` — the bench's JSON line): routing /
        failover / restart counters plus per-replica health codes,
        routed counts, predicted TTFT, and — when SLO monitors are
        wired — fleet-pooled latency percentiles."""
        out: Dict[str, float] = {
            "replicas": float(len(self.replicas)),
            "replicas_routable": float(
                sum(r.routable() for r in self.replicas)),
            "requests_completed": float(len(self.completions)),
            "routed": float(self._routed),
            "steps": float(self._steps),
            "failover_waves": float(self._failover_waves),
            "failed_over_requests": float(self._failed_over_requests),
            "aborted_requests": float(self._aborted_requests),
            "pending_failovers": float(len(self._pending)),
            "drains": float(self._drains),
            "restarts": float(self._restarts),
            "queue_full": float(self._queue_full),
            "incidents": float(len(self.incidents_written)),
            "health_state": float(self.health.code),
            "tokens_emitted": 0.0,
        }
        for rep in self.replicas:
            out[f"replica{rep.index}_health"] = float(
                HEALTH_STATES.index(rep.health_state))
            out[f"replica{rep.index}_routed"] = float(rep.routed)
            out[f"replica{rep.index}_predicted_ttft_s"] = \
                rep.sched.predicted_ttft_s()
            out["tokens_emitted"] += rep.sched.summary().get(
                "tokens_emitted", 0.0)
        if any(rep.sched.slo is not None for rep in self.replicas):
            from apex_tpu.telemetry.slo import METRICS as SLO_METRICS
            for metric in SLO_METRICS:
                for k, v in self.fleet_percentiles(metric).items():
                    out[f"fleet_slo_{metric}_{k}"] = v
        return out

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Release every replica's process-wide hooks (engine
        sentinels) and detach the eviction ownership. Idempotent."""
        for rep in self.replicas:
            rep.sched.on_evict = None
            rep.sched.engine.close()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
