"""Fault injection, failure isolation, and live health for serving.

The serving stack (engine + scheduler) is fast and observable but a
single escaped exception, NaN-poisoned batch, or hung dispatch used to
take down the whole engine and every in-flight request. Upstream apex's
core robustness idea — the amp dynamic loss scaler that *detects* bad
numerics and *recovers* instead of crashing (``apex/amp/scaler.py``
(U)) — transplants to serving as four pieces, all host-side (zero
change to the compiled programs, so the happy path pays nothing):

- :class:`FaultPlan` — a deterministic, replayable chaos harness: each
  engine seam (``admit`` / ``dispatch`` / ``fetch``, plus the
  scheduler's ``submit``) counts its calls, and a plan maps call
  indices to injected faults (raised device errors, NaN/invalid-token
  batches, artificial hangs, queue floods). Seeded plans
  (:meth:`FaultPlan.random`) make randomized chaos soaks exact reruns.
- Failure-domain isolation — a fault poisons the engine's donated
  cache/state buffers (:class:`EngineFault`); recovery rebuilds them
  from the compiled ``init`` program and deterministically *replays*
  interrupted requests from their prompts (the last known-good slot
  snapshot is the scheduler's host record: prompt + emitted tokens —
  generation is per-request deterministic, so the replayed stream is
  bit-identical and already-streamed tokens are simply re-derived and
  suppressed). Affected requests get bounded retries with exponential
  backoff and per-request ``error`` stream events.
- Overload protection — deadline-aware admission shedding (a queued
  request whose estimated wait already blows its deadline is shed NOW,
  not left to rot), structured :class:`~apex_tpu.serving.scheduler.
  QueueFull` backpressure with a retry-after hint, and a fetch
  watchdog that flags hung dispatches.
- :class:`HealthMonitor` — the ``ok → degraded → draining → failed``
  state machine driven by detected faults, watchdog trips, retry
  exhaustion, and queue saturation; exported as the
  ``serving_health_state`` gauge and as a ``/healthz`` callback for
  :class:`apex_tpu.telemetry.http.MetricsServer` (load-balancer
  semantics: ``ok``/``degraded`` answer 200, ``draining``/``failed``
  answer 503).

Dependency-free (stdlib only) so the chaos harness imports anywhere
the telemetry layer does.
"""

from __future__ import annotations

import dataclasses
import random as _random
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# -- fault vocabulary --------------------------------------------------------

#: a raised error at the seam (simulates an exception escaping the
#: device call; poisons the engine's donated buffers)
KIND_ERROR = "error"
#: an invalid-token batch (what a NaN logit batch produces downstream:
#: out-of-vocab token ids in the fetched host array)
KIND_NAN = "nan"
#: an artificial dispatch hang, observed at fetch (the watchdog's prey)
KIND_HANG = "hang"
#: a queue flood: the submit seam reports the queue saturated
KIND_FLOOD = "flood"

FAULT_KINDS = (KIND_ERROR, KIND_NAN, KIND_HANG, KIND_FLOOD)

#: engine seams (``admit``/``dispatch``/``fetch``/``retire``) + the
#: scheduler's intake seam (``submit``, the only place a flood makes
#: sense)
FAULT_POINTS = ("admit", "dispatch", "fetch", "retire", "submit")

#: which kinds are meaningful at which seam
_VALID = {
    "admit": (KIND_ERROR, KIND_NAN),
    "dispatch": (KIND_ERROR, KIND_HANG),
    "fetch": (KIND_ERROR, KIND_NAN, KIND_HANG),
    "retire": (KIND_ERROR,),
    "submit": (KIND_FLOOD,),
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One planned fault: the ``index``-th call at ``point`` (0-based,
    counted per seam) misbehaves as ``kind``. ``slots`` are the lanes an
    invalid-token batch corrupts (admit: batch rows; fetch: engine
    slots); ``hang_s`` is the artificial stall for ``hang`` faults;
    ``token`` is the injected out-of-vocab id (< 0 or >= vocab both
    detect)."""

    point: str
    index: int
    kind: str
    slots: Tuple[int, ...] = (0,)
    hang_s: float = 0.0
    token: int = -1

    def describe(self) -> str:
        extra = f" hang={self.hang_s}s" if self.kind == KIND_HANG else (
            f" slots={list(self.slots)}" if self.kind == KIND_NAN else "")
        return f"{self.kind}@{self.point}[{self.index}]{extra}"


class FaultPlan:
    """A deterministic schedule of injected faults over the engine's
    seams. Each seam keeps a monotonic call counter; :meth:`take`
    advances it and returns the planned :class:`FaultSpec` for that
    call, if any — so a plan replays EXACTLY given the same request
    trace (chaos tests are reruns, not dice rolls). ``hang_fn``
    implements the stall (default ``time.sleep``); tests inject a
    fake-clock advance instead, so hangs are deterministic too.

    >>> plan = FaultPlan([FaultSpec("fetch", 2, "nan", slots=(1,))])
    >>> eng = Engine(cfg, params, mesh, ecfg, fault_plan=plan)
    """

    def __init__(self, specs: Sequence[FaultSpec] = (), *,
                 hang_fn: Callable[[float], None] = time.sleep):
        by_point: Dict[str, Dict[int, FaultSpec]] = {}
        for s in specs:
            if s.point not in FAULT_POINTS:
                raise ValueError(
                    f"unknown fault point {s.point!r}; one of "
                    f"{FAULT_POINTS}")
            if s.kind not in _VALID[s.point]:
                raise ValueError(
                    f"fault kind {s.kind!r} not injectable at "
                    f"{s.point!r} (valid: {_VALID[s.point]})")
            if s.index < 0:
                raise ValueError(f"fault index {s.index} must be >= 0")
            slot = by_point.setdefault(s.point, {})
            if s.index in slot:
                raise ValueError(
                    f"duplicate fault at {s.point}[{s.index}] — one "
                    f"fault per (point, call) keeps plans replayable")
            slot[s.index] = s
        self._by_point = by_point
        self.hang_fn = hang_fn
        self._counts = {p: 0 for p in FAULT_POINTS}
        #: specs that actually fired, in firing order — the replay
        #: record chaos tests reconcile counters against
        self.injected: List[FaultSpec] = []
        #: optional observer called with each FaultSpec the moment it
        #: fires (the scheduler wires the flight recorder here, so a
        #: post-mortem bundle shows injections next to detections)
        self.on_inject: Optional[Callable[[FaultSpec], None]] = None

    @classmethod
    def random(cls, seed: int, n_faults: int = 3, *,
               points: Sequence[str] = ("admit", "dispatch", "fetch"),
               max_index: int = 24, slots: int = 4, hang_s: float = 0.0,
               hang_fn: Callable[[float], None] = time.sleep
               ) -> "FaultPlan":
        """A seeded random plan: ``n_faults`` faults scattered over
        ``points`` within the first ``max_index`` calls of each —
        bit-reproducible from ``seed`` (``random.Random``, no global
        state), so a failing soak reruns exactly."""
        rng = _random.Random(seed)
        specs: List[FaultSpec] = []
        used = set()
        while len(specs) < n_faults and len(used) < len(points) * max_index:
            point = rng.choice(list(points))
            index = rng.randrange(max_index)
            if (point, index) in used:
                continue
            used.add((point, index))
            kind = rng.choice(_VALID[point])
            specs.append(FaultSpec(
                point, index, kind,
                slots=(rng.randrange(max(slots, 1)),),
                hang_s=hang_s if kind == KIND_HANG else 0.0))
        return cls(specs, hang_fn=hang_fn)

    @property
    def specs(self) -> Tuple[FaultSpec, ...]:
        return tuple(s for by in self._by_point.values()
                     for s in by.values())

    def take(self, point: str) -> Optional[FaultSpec]:
        """Advance ``point``'s call counter; return the fault planned
        for this call (recording it in :attr:`injected`), or None."""
        i = self._counts[point]
        self._counts[point] = i + 1
        spec = self._by_point.get(point, {}).get(i)
        if spec is not None:
            self.injected.append(spec)
            if self.on_inject is not None:
                self.on_inject(spec)
        return spec

    def counts(self) -> Dict[str, int]:
        """Calls seen per seam so far (diagnostics / plan sizing)."""
        return dict(self._counts)

    def reset(self) -> "FaultPlan":
        """Rewind the counters and the firing record — the same plan
        replays over a fresh trace."""
        self._counts = {p: 0 for p in FAULT_POINTS}
        self.injected = []
        return self


def parse_fault_plan(text: str, *,
                     hang_fn: Callable[[float], None] = time.sleep
                     ) -> FaultPlan:
    """CLI surface for fault plans: either ``random:SEED[:N]`` or a
    comma list of ``point:index:kind[:arg]`` where ``arg`` is
    ``hang_s`` for hangs and a slot index for nan faults —
    e.g. ``"fetch:2:nan:1,dispatch:5:error"``."""
    text = text.strip()
    if text.startswith("random:"):
        parts = text.split(":")
        seed = int(parts[1])
        n = int(parts[2]) if len(parts) > 2 else 3
        return FaultPlan.random(seed, n, hang_fn=hang_fn)
    specs = []
    for item in text.split(","):
        parts = item.strip().split(":")
        if len(parts) < 3:
            raise ValueError(
                f"fault spec {item!r}: want point:index:kind[:arg]")
        point, index, kind = parts[0], int(parts[1]), parts[2]
        kw: Dict[str, object] = {}
        if len(parts) > 3:
            if kind == KIND_HANG:
                kw["hang_s"] = float(parts[3])
            else:
                kw["slots"] = (int(parts[3]),)
        specs.append(FaultSpec(point, index, kind, **kw))
    return FaultPlan(specs, hang_fn=hang_fn)


class FleetFaultPlan:
    """Per-replica :class:`FaultPlan` schedule for a fleet — the chaos
    harness lifted to the router level: replica ``i``'s engine is
    built with ``fleet_plan[i]``, every plan is independently
    deterministic, and a kill-one-replica-mid-burst soak replays
    exactly from its seed.

    >>> plans = FleetFaultPlan.kill(1, 2, at=4)   # replica 1 dies
    >>> engines = [Engine(cfg, params, mesh, ecfg,
    ...                   fault_plan=plans[i]) for i in range(2)]
    """

    def __init__(self, plans: Sequence[FaultPlan]):
        self.plans: Tuple[FaultPlan, ...] = tuple(plans)
        if not self.plans:
            raise ValueError("a fleet plan needs at least one replica")

    def __len__(self) -> int:
        return len(self.plans)

    def __getitem__(self, i: int) -> FaultPlan:
        return self.plans[i]

    def __iter__(self):
        return iter(self.plans)

    @classmethod
    def random(cls, seed: int, n_replicas: int, n_faults: int = 3,
               **kw) -> "FleetFaultPlan":
        """A seeded random plan per replica — derived seeds, so the
        whole fleet soak is bit-reproducible from one ``seed``."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas {n_replicas} must be >= 1")
        return cls([FaultPlan.random(seed * 1_000_003 + i, n_faults,
                                     **kw)
                    for i in range(n_replicas)])

    @classmethod
    def kill(cls, replica: int, n_replicas: int, *, at: int = 4,
             rebuilds: int = 4) -> "FleetFaultPlan":
        """Terminally fail ``replica`` at its ``at``-th decode
        dispatch: ``rebuilds`` consecutive dispatch errors with no
        healthy chunk between them exhaust the scheduler's
        ``max_consecutive_rebuilds`` (default 3, so the default
        ``rebuilds=4`` crosses it) and the health machine goes
        ``failed`` — deterministically, mid-burst. Every other
        replica's plan is empty.

        Pair the victim's scheduler with ``ResilienceConfig(
        max_retries >= rebuilds)``: with the default ``max_retries=2``
        a router's retry-exhaustion failover can move every live
        request OFF the replica after the third consecutive fault,
        leaving no traffic to consume the remaining dispatch indices —
        the replica then survives degraded instead of failing
        terminally (fine for the fleet, wrong for a kill drill). On a
        slow/throttled host, also raise ``watchdog_timeout_s``: two
        >timeout chunks trip the router's breaker and evict the victim
        the same way."""
        if not 0 <= replica < n_replicas:
            raise ValueError(
                f"replica {replica} outside fleet [0, {n_replicas})")
        specs = [FaultSpec("dispatch", at + j, KIND_ERROR)
                 for j in range(rebuilds)]
        return cls([FaultPlan(specs if i == replica else ())
                    for i in range(n_replicas)])

    @property
    def injected(self) -> List[FaultSpec]:
        """Every fault that fired, across replicas, in replica order."""
        return [s for p in self.plans for s in p.injected]

    def describe(self) -> str:
        return "; ".join(
            f"r{i}=[{', '.join(s.describe() for s in p.specs)}]"
            for i, p in enumerate(self.plans) if p.specs) or "no faults"

    def reset(self) -> "FleetFaultPlan":
        for p in self.plans:
            p.reset()
        return self


# -- exceptions --------------------------------------------------------------


class EngineFault(RuntimeError):
    """A failure at an engine seam that invalidates the donated
    cache/state buffers. The engine refuses further device calls until
    :meth:`~apex_tpu.serving.engine.Engine.rebuild_slots` reconstructs
    them (failure isolation: a poisoned buffer must never serve)."""

    def __init__(self, message: str, *, point: str = "",
                 spec: Optional[FaultSpec] = None):
        super().__init__(message)
        self.point = point
        self.spec = spec


class InjectedFault(EngineFault):
    """An :class:`EngineFault` raised by a :class:`FaultPlan` (chaos
    testing) rather than a real device failure."""


class EngineFailed(RuntimeError):
    """The health machine reached ``failed`` (terminal): recovery was
    exhausted and the scheduler aborted all work with ``error``
    outcomes. New submissions are refused."""


# -- health state machine ----------------------------------------------------

HEALTH_OK = "ok"
HEALTH_DEGRADED = "degraded"
HEALTH_DRAINING = "draining"
HEALTH_FAILED = "failed"

#: all states, in gauge-code order: ``serving_health_state`` exports
#: the tuple index (0 = ok .. 3 = failed)
HEALTH_STATES = (HEALTH_OK, HEALTH_DEGRADED, HEALTH_DRAINING,
                 HEALTH_FAILED)


class HealthMonitor:
    """The serving health state machine.

    Transitions: any detected fault / watchdog trip / queue saturation
    degrades (``ok → degraded``); ``recovery_chunks`` consecutive
    healthy decode-chunk fetches recover (``degraded → ok``);
    ``begin_drain``/``end_drain`` bracket a pipeline drain
    (``→ draining →`` back to whatever the state was, faults observed
    mid-drain land in the resume state); ``fail()`` is terminal. The
    ``serving_health_state`` gauge mirrors every transition when a
    registry is given, and :meth:`healthz` is the callback shape
    ``telemetry.http.MetricsServer(health=...)`` serves — 200 while
    traffic should keep flowing (ok/degraded), 503 when it should stop
    (draining/failed), body = the state name."""

    def __init__(self, *, registry=None, recovery_chunks: int = 2,
                 on_transition: Optional[
                     Callable[[str, str, Optional[str]], None]] = None):
        if recovery_chunks < 1:
            raise ValueError(
                f"recovery_chunks {recovery_chunks} must be >= 1")
        self.state = HEALTH_OK
        self.recovery_chunks = recovery_chunks
        self.last_cause: Optional[str] = None
        #: optional observer called AFTER each state change with
        #: ``(old, new, last_cause)`` — the scheduler wires the flight
        #: recorder + auto bundle dump here
        self.on_transition = on_transition
        self._resume = HEALTH_OK  # state a drain returns to
        self._streak = 0          # consecutive healthy chunks
        self._gauge = self._transitions = None
        if registry is not None:
            self._gauge = registry.gauge(
                "serving_health_state",
                "serving health: 0=ok 1=degraded 2=draining 3=failed")
            self._gauge.set(0)
            tr = registry.counter(
                "serving_health_transitions_total",
                "health state entries, by state", labels=("to",))
            # pre-create every state so scrapes show explicit zeros
            self._transitions = {s: tr.labels(to=s) for s in HEALTH_STATES}

    def _set(self, state: str) -> None:
        if state == self.state:
            return
        old, self.state = self.state, state
        if self._gauge is not None:
            self._gauge.set(HEALTH_STATES.index(state))
            self._transitions[state].inc()
        if self.on_transition is not None:
            self.on_transition(old, state, self.last_cause)

    # -- inputs -------------------------------------------------------------

    def record_fault(self, cause: str) -> None:
        """A detected fault / watchdog trip / overload signal: degrade
        (mid-drain: the drain continues, but resumes degraded)."""
        if self.state == HEALTH_FAILED:
            return
        self.last_cause = cause
        self._streak = 0
        if self.state == HEALTH_DRAINING:
            self._resume = HEALTH_DEGRADED
        else:
            self._set(HEALTH_DEGRADED)

    def record_progress(self) -> None:
        """One healthy decode chunk fetched end-to-end; enough of them
        in a row recover a degraded engine."""
        if self.state != HEALTH_DEGRADED:
            return
        self._streak += 1
        if self._streak >= self.recovery_chunks:
            self._set(HEALTH_OK)

    def begin_drain(self) -> None:
        if self.state in (HEALTH_FAILED, HEALTH_DRAINING):
            return
        self._resume = self.state
        self._set(HEALTH_DRAINING)

    def end_drain(self) -> None:
        if self.state == HEALTH_DRAINING:
            self._set(self._resume)

    def fail(self, cause: str) -> None:
        """Terminal: recovery exhausted."""
        self.last_cause = cause
        self._set(HEALTH_FAILED)

    # -- outputs ------------------------------------------------------------

    @property
    def code(self) -> int:
        return HEALTH_STATES.index(self.state)

    def healthz(self) -> Tuple[int, str]:
        """The ``MetricsServer(health=...)`` callback: (status code,
        body). 200 for ok/degraded (keep routing traffic), 503 for
        draining/failed (stop)."""
        status = 200 if self.state in (HEALTH_OK, HEALTH_DEGRADED) \
            else 503
        body = self.state + "\n"
        if self.state != HEALTH_OK and self.last_cause:
            body = f"{self.state} ({self.last_cause})\n"
        return status, body


# -- scheduler policy knobs --------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Recovery/overload policy for the scheduler, all host-side.
    ``max_retries`` bounds re-admissions per FAULT-AFFECTED request
    (requests merely interrupted by a batch-mate's fault replay for
    free — they were not at fault); backoff before retry ``n`` is
    ``backoff_base_s * backoff_factor**(n-1)`` on the scheduler clock.
    ``watchdog_timeout_s`` flags a decode chunk whose dispatch→fetch
    wall time exceeds it (a hung dispatch). ``shed_deadlines`` enables
    deadline-aware admission shedding (queue depth × measured chunk
    latency vs the request's deadline). ``max_consecutive_rebuilds``
    caps back-to-back recoveries with no healthy chunk between them
    before the engine is declared failed."""

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    watchdog_timeout_s: float = 30.0
    shed_deadlines: bool = True
    recovery_chunks: int = 2
    max_consecutive_rebuilds: int = 3

    def backoff_s(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        return self.backoff_base_s * (
            self.backoff_factor ** max(attempt - 1, 0))


# -- crash drill (subprocess SIGKILL + journal recovery) ----------------------

#: the drill child: a self-contained serving subprocess the parent can
#: SIGKILL mid-stream. "run" serves a deterministic request trace
#: (optionally journaled), printing one "TOKENS <n>" progress line per
#: scheduler step — the parent's kill trigger; "recover" rebuilds via
#: journal.recover_scheduler and serves to idle. Both end with one
#: "DONE <json>" line carrying every request's final stream (the
#: recover mode merges journal-finished requests with its own
#: completions, so the parent compares complete traces). Kept as
#: source, not a function, because the whole point is a separate
#: process to kill -9.
_DRILL_CHILD_SRC = '''\
"""Crash-drill child — spawned by resilience.sigkill_drill."""
import argparse
import json
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=["run", "recover"])
    ap.add_argument("--journal", default=None)
    ap.add_argument("--requests", type=int, default=3)
    ap.add_argument("--max-tokens", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7000)
    args = ap.parse_args()

    import jax
    from apex_tpu import mesh as mx
    from apex_tpu.models import gpt
    from apex_tpu.serving import Request, SamplingParams
    from apex_tpu.serving.engine import Engine, EngineConfig
    from apex_tpu.serving.journal import (Journal, recover_scheduler,
                                          replay_state, scan_journal)
    from apex_tpu.serving.scheduler import Scheduler
    from apex_tpu.transformer.testing import standalone_gpt_config

    VOCAB = 96
    cfg = standalone_gpt_config(vocab_size=VOCAB, seq_len=64)
    params = gpt.init(cfg, jax.random.PRNGKey(0))
    mesh = mx.build_mesh(tp=1, devices=jax.devices()[:1])

    def build():
        return Engine(cfg, params, mesh,
                      EngineConfig(slots=2, max_prompt_len=8,
                                   max_seq_len=24, decode_chunk=2))

    def reqs():
        out = []
        for i in range(args.requests):
            p_len = 2 + (3 * i) % 6
            prompt = [int(t) for t in jax.random.randint(
                jax.random.PRNGKey(args.seed + i), (p_len,), 0, VOCAB)]
            sp = (SamplingParams(temperature=0.9, top_k=7,
                                 seed=args.seed + i)
                  if i % 2 else SamplingParams())
            out.append(Request(f"d{i}", prompt,
                               max_tokens=args.max_tokens, sampling=sp))
        return out

    extra = {}
    if args.mode == "run":
        eng = build().warmup()
        j = Journal(args.journal) if args.journal else None
        sched = Scheduler(eng, journal=j)
        for r in reqs():
            sched.submit(r)
        while not sched.idle():
            sched.step()
            # the parent's kill trigger: one progress line per step
            print("TOKENS", sched._tokens_emitted, flush=True)
    else:
        t0 = time.monotonic()
        sched, report = recover_scheduler(args.journal, build)
        extra["recovery_ms"] = (time.monotonic() - t0) * 1e3
        extra["report"] = report.as_dict()
        # requests that finished BEFORE the crash live only in the
        # journal now — merge them so DONE carries the full trace the
        # client saw across both processes
        state = replay_state(scan_journal(args.journal)[0])
        for rid, rq in state.requests.items():
            if rq["finished"]:
                extra.setdefault("prior", {})[rid] = list(rq["emitted"])
        while not sched.idle():
            sched.step()
        extra["journal_fsync_ms"] = sched.journal.fsync_s * 1e3
    done = {rid: {"tokens": list(c.tokens), "reason": c.finish_reason}
            for rid, c in sched.completions.items()}
    print("DONE " + json.dumps({"completions": done, **extra}),
          flush=True)


if __name__ == "__main__":
    main()
'''


def sigkill_drill(workdir: str, *, requests: int = 3,
                  max_tokens: int = 10, kill_after_tokens: int = 6,
                  seed: int = 7000, timeout_s: float = 900.0,
                  python: Optional[str] = None) -> Dict[str, object]:
    """The crash drill the journal's whole design is judged by: spawn
    a serving subprocess journaling to ``workdir/journal``, ``kill
    -9`` it once ``kill_after_tokens`` tokens have streamed, restart
    from the journal in a fresh subprocess, and compare every
    request's end-to-end stream against an uninterrupted reference
    run. Returns::

        {"parity": bool, "killed_at_tokens": int, "recovery_ms": ...,
         "journal_fsync_ms": ..., "recovered_requests": int,
         "reference": {rid: [tok, ...]}, "recovered": {rid: [...]}}

    Children run on one forced-CPU device with the persistent compile
    cache DISABLED (restoring cached executables in subprocess smokes
    corrupts this runtime's heap — see tests/conftest.py), so each
    child pays a cold compile: minutes, not seconds. Slow-marked
    tests and ``bench.py --mode serve --crash`` are the callers."""
    import json as _json
    import os
    import subprocess
    import sys

    import apex_tpu

    os.makedirs(workdir, exist_ok=True)
    child = os.path.join(workdir, "drill_child.py")
    with open(child, "w", encoding="utf-8") as f:
        f.write(_DRILL_CHILD_SRC)
    journal_dir = os.path.join(workdir, "journal")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        apex_tpu.__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_COMPILATION_CACHE_DIR"] = ""     # empty = disabled
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    py = python or sys.executable
    base = [py, child, "--requests", str(requests),
            "--max-tokens", str(max_tokens), "--seed", str(seed)]

    def _done_line(text: str) -> Dict[str, object]:
        for line in text.splitlines():
            if line.startswith("DONE "):
                return _json.loads(line[5:])
        raise RuntimeError(f"drill child printed no DONE line:\n{text}")

    # 1) uninterrupted reference (no journal — also the A side of
    #    "recovery changes nothing")
    ref = subprocess.run(base + ["run"], env=env, capture_output=True,
                         text=True, timeout=timeout_s)
    if ref.returncode != 0:
        raise RuntimeError(f"reference run failed:\n{ref.stderr}")
    reference = {rid: c["tokens"]
                 for rid, c in _done_line(ref.stdout)["completions"].items()}

    # 2) victim: journaled, killed -9 mid-stream on the progress line
    victim = subprocess.Popen(
        base + ["run", "--journal", journal_dir], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    killed_at = -1
    try:
        assert victim.stdout is not None
        for line in victim.stdout:
            if line.startswith("TOKENS "):
                n = int(line.split()[1])
                if n >= kill_after_tokens:
                    killed_at = n
                    victim.kill()   # SIGKILL — no atexit, no flush
                    break
            elif line.startswith("DONE "):
                break   # finished before the threshold — no kill
    finally:
        victim.wait(timeout=timeout_s)
    if killed_at < 0:
        raise RuntimeError(
            f"victim finished before streaming {kill_after_tokens} "
            f"tokens — lower kill_after_tokens or raise max_tokens")

    # 3) recover from the journal in a fresh process
    rec = subprocess.run(base + ["recover", "--journal", journal_dir],
                         env=env, capture_output=True, text=True,
                         timeout=timeout_s)
    if rec.returncode != 0:
        raise RuntimeError(f"recovery run failed:\n{rec.stderr}")
    payload = _done_line(rec.stdout)
    recovered = {rid: c["tokens"]
                 for rid, c in payload["completions"].items()}
    recovered.update(payload.get("prior", {}))
    parity = recovered == reference
    return {
        "parity": parity,
        "killed_at_tokens": killed_at,
        "recovery_ms": payload.get("recovery_ms"),
        "journal_fsync_ms": payload.get("journal_fsync_ms"),
        "recovered_requests": int(
            payload.get("report", {}).get("requests", 0)),
        "reference": reference,
        "recovered": recovered,
    }
