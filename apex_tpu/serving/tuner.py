"""Self-tuning serving runtime — the scheduler's knob control plane.

``decode_chunk``, ``pipeline_depth``, ``max_admit_batch``, and
``spec_k`` used to be hand-set constants frozen at engine construction:
one static operating point across bursty, shifting traffic. The PR-8
``_SpecGate`` proved the alternative on ONE knob — wall-time EWMAs of
both pre-warmed compiled variants, symmetric re-probing, hysteresis —
and this module promotes that pattern into the general mechanism:

- :class:`TunerConfig` declares, per knob, a static candidate ladder
  (e.g. ``decode_chunk in (4, 8, 16)``). Device-shaping knobs
  (:data:`VARIANT_KNOBS`) must name only values the engine pre-warmed
  (``EngineConfig.decode_chunks`` / ``spec_ks`` — every ladder member
  is one compiled step variant ``Engine.warmup()`` compiles and the
  recompile sentinel tracks), so the controller only ever switches
  among warm programs and an armed recompile guard stays flat.
- :class:`Controller` is the live state machine: a wall-time EWMA of
  realized tokens-per-second at each operating point, measuring →
  steady → probing states, one knob moved per probe window (coordinate
  descent — no combinatorial search), probes serialized to one
  in-flight chunk (except the ``pipeline_depth`` knob, whose candidate
  IS the in-flight depth), margin hysteresis on every switch, and hard
  freezes — revert to the BASE operating point, observations ignored —
  during constrained decoding, fault replay, rebuilds, and drain (the
  same exclusions the spec gate honors).
- every decision (probe start/end/abort, switch, freeze) is recorded
  as a flight-recorder event WITH the triggering EWMAs, and every
  observation the decisions derive from is recorded too
  (``tuner_obs``), so :func:`replay_decisions` can re-run the
  controller from a post-mortem bundle's recorded clocks and reproduce
  the decision sequence bit-identically — a bad tuning trajectory is a
  replayable incident, not an anecdote.

The module is import-light (stdlib only — no jax, no numpy): the
``telemetry.replay`` report path must be able to re-run a bundle's
tuning decisions on a laptop that has never seen the toolchain.
Validation against the engine's warmed ladders lives in the scheduler
(which holds the engine); the pure arithmetic lives here.

Measurement convention: one sample per fetched chunk,
``tokens * depth_at_dispatch / chunk_wall`` — the depth normalization
makes samples comparable across operating points (at depth d the
dispatch-to-fetch wall includes waiting behind d-1 earlier chunks),
while still crediting depth for the host time it hides (a depth-1
chunk's wall carries the host gap a pipelined chunk overlaps away).
Tokens are the chunk's ACTUAL ingested emissions, so a chunk too wide
for the slots' remaining budgets is honestly charged for its pad
columns. Watchdog-tripped chunks are excluded upstream, exactly like
the overload EWMA.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

#: the tunable knobs, in canonical order (the order point keys
#: serialize in, and the coordinate-descent round-robin order)
KNOBS: Tuple[str, ...] = ("decode_chunk", "pipeline_depth",
                          "max_admit_batch", "spec_k")

#: knobs whose candidate values select a COMPILED device program
#: variant, mapped to the engine's program-family attribute that holds
#: the pre-warmed variants. The scheduler validates every declared
#: candidate against the engine's resolved ladder, and the
#: WARMUP-COVERAGE lint rule statically pins the other half of the
#: contract: each named family must be reachable from
#: ``Engine.warmup()``'s call closure and tracked by
#: ``compiled_cache_sizes()``/the recompile sentinel — so a ladder can
#: never name a variant that would compile (and trip the armed guard)
#: mid-serve. Host-level knobs (``pipeline_depth``,
#: ``max_admit_batch``) shape no program and need no warm variant.
VARIANT_KNOBS: Dict[str, str] = {
    "decode_chunk": "_step_variants",
    "spec_k": "_spec_variants",
}

#: ``serving_tuner_state`` gauge values
TUNER_FROZEN, TUNER_MEASURING, TUNER_STEADY, TUNER_PROBING = \
    0.0, 1.0, 2.0, 3.0


@dataclasses.dataclass(frozen=True)
class TunerConfig:
    """Knob ladders + controller policy. A ``None`` ladder leaves that
    knob untouched at its configured value; a declared ladder must
    contain the configured value (the BASE operating point the
    controller starts from and hard-freezes back to).

    ``max_admit_batch`` ladders use ``0`` for "unlimited" (the
    scheduler's ``max_admit_batch=None``); ``spec_k`` ladders use ``0``
    for the plain step variant, and every non-zero rung must be a
    compiled ``EngineConfig.spec_ks`` variant."""

    #: tokens per compiled decode dispatch — each rung must be in
    #: ``EngineConfig.decode_chunks`` (a pre-warmed step variant)
    decode_chunk: Optional[Tuple[int, ...]] = None
    #: decode chunks kept in flight by the scheduler (host knob)
    pipeline_depth: Optional[Tuple[int, ...]] = None
    #: admission-wave cap (host knob; 0 = unlimited)
    max_admit_batch: Optional[Tuple[int, ...]] = None
    #: speculative draft width — 0 = plain; non-zero rungs must be in
    #: ``EngineConfig.spec_ks``. Owning this knob replaces the
    #: ``_SpecGate`` (one controller per knob, never two).
    spec_k: Optional[Tuple[int, ...]] = None
    #: weight of the newest tokens-per-second sample in every EWMA
    ewma_alpha: float = 0.3
    #: a challenger displaces the incumbent only when its EWMA clears
    #: the incumbent's by this factor (hysteresis — staying is free)
    margin: float = 1.05
    #: incumbent chunks between probe windows — the symmetric re-probe
    #: cadence: every candidate is re-measured on this beat, and the
    #: incumbent's own EWMA refreshes continuously in between, so
    #: neither side ever goes stale
    probe_every: int = 32
    #: chunks measured per probe window before the switch/revert
    #: decision
    probe_chunks: int = 4
    #: incumbent chunks measured before the controller probes at all
    min_measure_chunks: int = 4

    def ladders(self) -> List[Tuple[str, Tuple[int, ...]]]:
        """Declared ``(knob, candidates)`` pairs in :data:`KNOBS`
        order."""
        out = []
        for name in KNOBS:
            v = getattr(self, name)
            if v is not None:
                out.append((name, tuple(int(x) for x in v)))
        return out


def ewma(prev: float, sample: float, alpha: float) -> float:
    """THE zero-bootstrap EWMA (first sample seeds it) — one spelling
    shared by the Controller and the scheduler's ``_SpecGate`` so the
    two controllers' break-even arithmetic can never drift apart."""
    return sample if prev == 0.0 else (1 - alpha) * prev + alpha * sample


def point_key(point: Dict[str, int]) -> str:
    """Canonical string form of an operating point (the ``tuner_obs``
    event field): ``"decode_chunk=8,spec_k=0"`` in :data:`KNOBS`
    order."""
    return ",".join(f"{k}={point[k]}" for k in KNOBS if k in point)


def parse_point(key: str) -> Dict[str, int]:
    """Inverse of :func:`point_key`."""
    out: Dict[str, int] = {}
    for part in key.split(","):
        if part:
            k, _, v = part.partition("=")
            out[k] = int(v)
    return out


class Controller:
    """The live knob state machine — pure host arithmetic; its output
    only ever picks which PRE-WARMED compiled variant (and host
    depth/admit-cap) the next dispatch uses.

    ``base`` is the configured operating point (one value per declared
    knob); it is both the starting incumbent and the hard-freeze
    fallback. ``recorder`` (optional, a
    :class:`~apex_tpu.telemetry.flightrec.FlightRecorder`) receives
    ``tuner_obs`` per observation and ``tuner_probe`` / ``tuner_switch``
    / ``tuner_freeze`` per decision; ``on_switch(knob)`` is the
    telemetry counter hook.

    The scheduler drives three entry points per chunk:
    :meth:`want_dispatch` before dispatching (``None`` = hold this
    tick, a probe chunk is still in flight), :meth:`observe` after the
    fetch, and :meth:`freeze`/:meth:`thaw` as the exclusion conditions
    come and go. All state transitions happen inside
    ``observe``/``freeze``/``thaw`` — every input is recorded, which is
    what makes :func:`replay_decisions` exact."""

    __slots__ = ("cfg", "knobs", "base", "incumbent", "ewma",
                 "incumbent_ewma", "samples", "since_probe", "probe",
                 "probe_seen", "probes_total", "switch_counts",
                 "frozen", "recorder", "on_switch", "_knob_order",
                 "_knob_i", "_cand_i", "ttft_ewma", "ttft_counts")

    def __init__(self, cfg: TunerConfig, base: Dict[str, int], *,
                 recorder=None,
                 on_switch: Optional[Callable[[str], None]] = None):
        ladders = cfg.ladders()
        if not ladders:
            raise ValueError(
                "TunerConfig declares no knob ladder — nothing to tune")
        if not 0.0 < cfg.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha {cfg.ewma_alpha} outside (0, 1]")
        if cfg.margin < 1.0:
            raise ValueError(
                f"margin {cfg.margin} must be >= 1.0 (a sub-unity margin "
                f"would switch on measurements WORSE than the incumbent)")
        for n in ("probe_every", "probe_chunks", "min_measure_chunks"):
            if getattr(cfg, n) < 1:
                raise ValueError(f"{n} {getattr(cfg, n)} must be >= 1")
        self.cfg = cfg
        self.knobs: Dict[str, Tuple[int, ...]] = {}
        for name, cands in ladders:
            lo = 1 if name in ("decode_chunk", "pipeline_depth") else 0
            if list(cands) != sorted(set(cands)) or (
                    cands and cands[0] < lo):
                raise ValueError(
                    f"{name} ladder must be strictly increasing with "
                    f"values >= {lo}, got {cands}")
            if name not in base:
                raise ValueError(
                    f"no base value for declared knob {name!r}")
            if int(base[name]) not in cands:
                raise ValueError(
                    f"base {name}={base[name]} is not on its ladder "
                    f"{cands} — the configured operating point must be "
                    f"a candidate (it is the freeze fallback)")
            self.knobs[name] = cands
        self.base = {k: int(base[k]) for k in self.knobs}
        self.incumbent = dict(self.base)
        #: tokens-per-second EWMA per (knob, candidate) — refreshed
        #: whenever a chunk runs with that candidate active (incumbent
        #: chunks refresh every incumbent value; probe chunks refresh
        #: the challenged one)
        self.ewma: Dict[Tuple[str, int], float] = {}
        #: tokens-per-second EWMA of the FULL incumbent operating
        #: point — the side every challenger must clear by ``margin``
        self.incumbent_ewma = 0.0
        self.samples = 0
        self.since_probe = 0
        #: the active probe window, (knob, candidate) — None in
        #: measuring/steady
        self.probe: Optional[Tuple[str, int]] = None
        self.probe_seen = 0
        self.probes_total = 0
        self.switch_counts: Dict[str, int] = {k: 0 for k in self.knobs}
        #: TTFT EWMA per full operating point (point_key → seconds) —
        #: OBSERVATION only this round: the admission knobs shape TTFT,
        #: not decode tok/s (DESIGN "Serving round 10"), so a future
        #: latency-aware policy needs per-point TTFT measured alongside
        #: the tok/s EWMAs before it can earn movement. Decisions still
        #: derive exclusively from tok/s.
        self.ttft_ewma: Dict[str, float] = {}
        self.ttft_counts: Dict[str, int] = {}
        #: freeze cause while hard-frozen (None = live)
        self.frozen: Optional[str] = None
        self.recorder = recorder
        self.on_switch = on_switch
        # coordinate-descent cursor: knobs round-robin, candidates
        # cycle within each knob (skipping the incumbent at pick time)
        self._knob_order = [k for k, c in self.knobs.items()
                            if len(c) > 1]
        if not self._knob_order:
            raise ValueError(
                f"every declared ladder has a single candidate "
                f"({ {k: v for k, v in self.knobs.items()} }) — "
                f"nothing can ever be probed; a silently inert "
                f"controller would read as autotuning that is not "
                f"happening")
        self._knob_i = 0
        self._cand_i = {k: 0 for k in self._knob_order}

    # -- the dispatch side ---------------------------------------------------

    def current_point(self) -> Dict[str, int]:
        """The operating point the next dispatch WOULD run (ignoring
        probe serialization): base while frozen, the probe point during
        a probe window, the incumbent otherwise. The scheduler applies
        its host-level knobs (depth, admit cap) from this each tick."""
        if self.frozen is not None:
            return dict(self.base)
        if self.probe is not None:
            p = dict(self.incumbent)
            p[self.probe[0]] = self.probe[1]
            return p
        return dict(self.incumbent)

    def want_dispatch(self, inflight: int) -> Optional[Dict[str, int]]:
        """The operating point for the next chunk, or ``None`` to hold
        the dispatch this tick: probe chunks are serialized to ONE in
        flight (clean walls, and no mixing of operating points inside a
        window) — except when the probed knob is ``pipeline_depth``,
        whose candidate IS the in-flight depth being measured."""
        if self.frozen is None and self.probe is not None \
                and self.probe[0] != "pipeline_depth" and inflight > 0:
            return None
        return self.current_point()

    # -- the fetch side ------------------------------------------------------

    def observe(self, point: Dict[str, int], tokens: int, wall_s: float,
                depth: int) -> None:
        """Fold one fetched chunk's measurement into the EWMAs and run
        any decision it triggers (probe end → switch/revert, probe
        start). ``point`` is the operating point the chunk was
        DISPATCHED at (attribution is per chunk, so leftovers from a
        pre-switch point never pollute the new incumbent's EWMA).
        Recorded as a ``tuner_obs`` event — the replayable input every
        decision derives from. Ignored while frozen (constrained /
        replay / rebuild traffic is atypical by construction; folding
        it in would poison the EWMAs the freeze exists to protect)."""
        if self.frozen is not None:
            return
        if self.recorder is not None:
            self.recorder.record("tuner_obs", point_key(point),
                                 int(tokens), float(wall_s), int(depth))
        self._observe(point, tokens, wall_s, depth)

    def _observe(self, point: Dict[str, int], tokens: int,
                 wall_s: float, depth: int) -> None:
        """The recording-free arithmetic (the half
        :func:`replay_decisions` re-runs on recorded inputs)."""
        if self.frozen is not None or tokens <= 0 or wall_s <= 0.0:
            return
        point = {k: point[k] for k in self.knobs}
        sample = tokens * max(depth, 1) / wall_s
        if self.probe is not None:
            knob, val = self.probe
            probe_point = dict(self.incumbent)
            probe_point[knob] = val
            if point == probe_point:
                key = (knob, val)
                self.ewma[key] = self._ewma(self.ewma.get(key, 0.0),
                                            sample)
                self.probe_seen += 1
                if self.probe_seen >= self.cfg.probe_chunks:
                    self._decide()
                return
            # a leftover chunk from another point landing mid-window:
            # attribute it (below) but never let it advance the window
        if point != self.incumbent:
            return  # stale pre-switch chunk — no attribution
        self.incumbent_ewma = self._ewma(self.incumbent_ewma, sample)
        for k, v in point.items():
            self.ewma[(k, v)] = self._ewma(self.ewma.get((k, v), 0.0),
                                           sample)
        self.samples += 1
        if self.probe is not None \
                or self.samples < self.cfg.min_measure_chunks:
            return
        self.since_probe += 1
        if self.since_probe >= self.cfg.probe_every:
            self._start_probe()

    def _ewma(self, prev: float, sample: float) -> float:
        return ewma(prev, sample, self.cfg.ewma_alpha)

    def observe_ttft(self, ttft_s: float) -> None:
        """Fold one request's time-to-first-token into the EWMA of the
        operating point it admitted under (:meth:`current_point` — the
        point the admission dispatch ran). Pure observation: no
        decision reads it yet (latency-aware control is the declared
        next step, and it needs this record to exist first). Ignored
        while frozen, like :meth:`observe` — freeze-window traffic is
        atypical by construction."""
        if self.frozen is not None or ttft_s <= 0.0:
            return
        key = point_key(self.current_point())
        if self.recorder is not None:
            self.recorder.record("tuner_ttft", key, float(ttft_s))
        self.ttft_ewma[key] = ewma(self.ttft_ewma.get(key, 0.0),
                                   ttft_s, self.cfg.ewma_alpha)
        self.ttft_counts[key] = self.ttft_counts.get(key, 0) + 1

    def ttft_by_point(self) -> Dict[str, Dict[str, float]]:
        """Per-operating-point TTFT observations:
        ``{point_key: {"ttft_ewma_s", "count"}}`` — the record the next
        round's latency-aware policy will read."""
        return {k: {"ttft_ewma_s": self.ttft_ewma[k],
                    "count": float(self.ttft_counts.get(k, 0))}
                for k in sorted(self.ttft_ewma)}

    # -- decisions -----------------------------------------------------------

    def _start_probe(self) -> None:
        """Open the next probe window: ONE knob moved to its next
        non-incumbent candidate (coordinate descent — knobs round-
        robin, candidates cycle within each knob)."""
        for _ in range(len(self._knob_order)):
            knob = self._knob_order[self._knob_i]
            self._knob_i = (self._knob_i + 1) % len(self._knob_order)
            cands = [v for v in self.knobs[knob]
                     if v != self.incumbent[knob]]
            if not cands:
                continue
            val = cands[self._cand_i[knob] % len(cands)]
            self._cand_i[knob] += 1
            self.probe = (knob, val)
            self.probe_seen = 0
            self.probes_total += 1
            if self.recorder is not None:
                self.recorder.record("tuner_probe", knob, val, "start",
                                     self.ewma.get((knob, val), 0.0),
                                     self.incumbent_ewma)
            # the window measures THIS regime only: a candidate EWMA
            # left over from another workload phase (or another
            # incumbent on the other knobs) would carry
            # (1-alpha)^probe_chunks stale weight into a 5%-margin
            # decision — fresh window, fresh measurement; freshness
            # across regimes is the re-probe cadence's job
            self.ewma.pop((knob, val), None)
            return

    def _decide(self) -> None:
        """Close the probe window: the challenger displaces the
        incumbent only when its EWMA clears the incumbent's by
        ``margin`` (hysteresis — reverting costs nothing, so a noisy
        tie keeps the devil we know)."""
        knob, val = self.probe
        cand = self.ewma.get((knob, val), 0.0)
        inc = self.incumbent_ewma
        self.probe = None
        self.probe_seen = 0
        self.since_probe = 0
        if self.recorder is not None:
            self.recorder.record("tuner_probe", knob, val, "end", cand,
                                 inc)
        if inc > 0.0 and cand > inc * self.cfg.margin:
            old = self.incumbent[knob]
            self.incumbent[knob] = val
            self.switch_counts[knob] += 1
            if self.recorder is not None:
                self.recorder.record("tuner_switch", knob, old, val,
                                     cand, inc)
            if self.on_switch is not None:
                self.on_switch(knob)
            # the probe window measured exactly the new full operating
            # point — seed the incumbent EWMA from it (it keeps
            # refreshing every incumbent chunk from here)
            self.incumbent_ewma = cand

    # -- hard freezes --------------------------------------------------------

    def freeze(self, cause: str) -> None:
        """Hard-freeze to the BASE operating point: an active probe
        window is aborted (no decision from partial, atypical data) and
        observations are ignored until :meth:`thaw`. Idempotent per
        cause; a cause CHANGE records a fresh enter event (the replay
        input stream must see it)."""
        if self.frozen == cause:
            return
        if self.frozen is None and self.probe is not None:
            knob, val = self.probe
            self.probe = None
            self.probe_seen = 0
            if self.recorder is not None:
                self.recorder.record("tuner_probe", knob, val, "abort",
                                     self.ewma.get((knob, val), 0.0),
                                     self.incumbent_ewma)
        self.frozen = cause
        if self.recorder is not None:
            self.recorder.record("tuner_freeze", "enter", cause)

    def thaw(self) -> None:
        """Lift a freeze (no-op when live)."""
        if self.frozen is None:
            return
        if self.recorder is not None:
            self.recorder.record("tuner_freeze", "exit", self.frozen)
        self.frozen = None

    # -- reporting -----------------------------------------------------------

    def state(self) -> float:
        """``serving_tuner_state`` gauge value: 0 frozen, 1 measuring,
        2 steady, 3 probing."""
        if self.frozen is not None:
            return TUNER_FROZEN
        if self.probe is not None:
            return TUNER_PROBING
        if self.samples < self.cfg.min_measure_chunks:
            return TUNER_MEASURING
        return TUNER_STEADY


#: event names the controller emits as decisions (everything except
#: the ``tuner_obs`` inputs) — the sequence replay compares
DECISION_EVENTS = ("tuner_probe", "tuner_switch", "tuner_freeze")


def _event_fields(ev: Dict[str, Any]) -> List[Any]:
    from apex_tpu.telemetry.flightrec import EVENT_FIELDS

    return [ev.get(f) for f in EVENT_FIELDS[ev["event"]]]


def replay_decisions(cfg: TunerConfig, base: Dict[str, int],
                     events: Iterable[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Re-run a fresh :class:`Controller` over a bundle's recorded
    inputs — ``tuner_obs`` observations and ``tuner_freeze``
    enter/exit transitions, in recorded sequence order — and return
    the decision events it regenerates. Pure host arithmetic on
    recorded clocks: bit-identical to the original run's decisions by
    construction (the comparison :func:`compare_decisions` asserts)."""
    from apex_tpu.telemetry.flightrec import FlightRecorder

    rec = FlightRecorder(clock=lambda: 0.0)
    ctl = Controller(cfg, base, recorder=rec)
    for ev in events:
        name = ev.get("event")
        if name == "tuner_obs":
            ctl._observe(parse_point(ev["point"]), ev["tokens"],
                         ev["wall_s"], ev["depth"])
        elif name == "tuner_freeze":
            if ev.get("phase") == "enter":
                ctl.freeze(ev.get("cause"))
            else:
                ctl.thaw()
    return [e for e in rec.to_dicts(rec.events())
            if e["event"] in DECISION_EVENTS]


def compare_decisions(cfg: TunerConfig, base: Dict[str, int],
                      events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The bundle-side check: replay the recorded inputs and compare
    the regenerated decision sequence against the recorded one,
    seq-for-seq and field-for-field. Returns the machine-readable
    verdict (``mismatches`` empty = the trajectory replays exactly)."""
    events = sorted(events, key=lambda e: e.get("seq", 0))
    recorded = [e for e in events if e.get("event") in DECISION_EVENTS]
    replayed = replay_decisions(cfg, base, events)
    mismatches: List[Dict[str, Any]] = []
    for i in range(max(len(recorded), len(replayed))):
        a = recorded[i] if i < len(recorded) else None
        b = replayed[i] if i < len(replayed) else None
        if a is None or b is None or a["event"] != b["event"] \
                or _event_fields(a) != _event_fields(b):
            mismatches.append({"index": i, "recorded": a,
                               "replayed": b})
    return {
        "decisions_recorded": len(recorded),
        "decisions_replayed": len(replayed),
        "mismatches": mismatches,
    }
