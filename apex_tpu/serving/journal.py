"""Durable serving: the write-ahead request journal + crash-safe
warm restart.

Every fault-tolerance layer shipped so far — ``rebuild_slots``
isolation (PR 5), fleet failover with emitted-prefix handoff (PR 12),
host-swap preemption replay (PR 19) — lives and dies with the process:
a SIGKILL or host reboot loses every in-flight stream, registered
adapter, and parked conversation, and post-mortem bundles only explain
the loss afterwards. This module makes the recovery contract survive
the process: a segmented, CRC-framed append-only log records every
durable-relevant host decision, and :func:`recover_scheduler` rebuilds
a fresh engine + scheduler from it so client streams continue
**bit-identically across a process death** — the same grow-only
emitted-prefix snapshot the fault machinery replays from, made
durable. Upstream apex's loss-scaler philosophy (detect → isolate →
recover without losing the run, ``apex/amp/scaler.py`` (U)) carried to
its cross-process conclusion; crash-restart is also the
request-migration substrate the ROADMAP's prefill/decode
disaggregation item builds on.

Record framing (one record)::

    [u32 payload length][u32 crc32(payload)][payload: compact JSON]

Payloads are JSON objects ``{"seq": n, "kind": k, ...fields}``. Kinds:

- ``meta`` — format version + the engine spec subset of
  :meth:`Engine.describe` (model/engine/tp), so recovery can refuse an
  incompatible engine before resubmitting anything (the PR-15
  describe()/replay idiom).
- ``submit`` — prompt/sampling/seed/eos/stop/tenant/adapter plus the
  deadline REMAINING at submit (absolute clocks do not survive a
  restart; recovery re-bases them).
- ``extend`` — the grow-only emitted-prefix snapshot's growth since
  the last journaled length, logged at fetch boundaries:
  ``{request_id, start, tokens, logprobs}``. Extends carry ABSOLUTE
  start offsets so replaying a record twice (a crash between
  compaction's write and its old-segment cleanup) is idempotent.
- ``finish`` — terminal outcome (eos/length/stop/timeout/error, or
  ``evicted`` when a fleet failover took the work); recovery skips
  finished requests.
- ``park`` / ``resume`` — the host-swap oversubscription lifecycle;
  a parked conversation recovers as a queued resubmission.
- ``adapter`` / ``prefix`` — pool registrations. Seeded adapters
  re-derive bit-identically from the recorded seed; explicit-weight
  registrations record ``seed: null`` and recovery counts them as
  unreplayable (their requests are skipped with a counted stat).

Torn-tail recovery: scanning stops at the first incomplete header,
short payload, or CRC mismatch; opening a journal for append (and
:func:`scan_journal` with ``repair=True``) truncates the torn segment
at the last complete record and removes any later segments — zero
duplicate and zero lost *committed* records. Tokens appended after the
last fsync may be lost with the page cache; recovery simply re-derives
them (deterministic generation), so the continued stream is still
bit-identical.

Fsync policy prices durability: ``always`` fsyncs every append,
``batch`` fsyncs once per fetch boundary (the scheduler's
:meth:`~apex_tpu.serving.scheduler.Scheduler` commit point — the
default), ``none`` never fsyncs (page-cache durability only).
Compacted segments and the manifest are finalized through
:mod:`apex_tpu._atomic`, the shared crash-safe write helper.

Stdlib-only by the telemetry contract — scanning and compaction run on
a laptop with no jax installed; :func:`recover_scheduler` imports the
serving stack lazily.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from apex_tpu import _atomic

__all__ = [
    "FORMAT_VERSION", "FSYNC_POLICIES", "Journal", "JournalError",
    "JournalState", "RecoveryReport", "recover_scheduler",
    "replay_into", "replay_state", "scan_journal",
]

#: bump on any incompatible record-schema change; recovery refuses a
#: journal whose meta record claims a newer format
FORMAT_VERSION = 1

FSYNC_POLICIES = ("none", "batch", "always")

#: per-record frame: little-endian u32 payload length + u32 crc32
_FRAME = struct.Struct("<II")

#: a length prefix past this is torn garbage, not a record (the
#: largest real record is a long prompt — a few hundred KiB)
_MAX_RECORD = 64 * 1024 * 1024

_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".wal"
_MANIFEST = "journal.json"


class JournalError(ValueError):
    """A journal that cannot be appended to or recovered from."""


def _seg_name(index: int) -> str:
    return f"{_SEG_PREFIX}{index:08d}{_SEG_SUFFIX}"


def _seg_index(name: str) -> Optional[int]:
    if not (name.startswith(_SEG_PREFIX)
            and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


def _frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def _encode(rec: Dict[str, Any]) -> bytes:
    # default=str: engine-spec dicts may carry dtype objects; recovery
    # compares the round-tripped JSON on both sides, so stringifying
    # is lossless for the compatibility check
    return json.dumps(rec, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def _segments(path: str) -> List[Tuple[int, str]]:
    """Sorted ``(index, filename)`` of the segment files under
    ``path``."""
    out = []
    for name in os.listdir(path):
        idx = _seg_index(name)
        if idx is not None:
            out.append((idx, name))
    out.sort()
    return out


def _scan_file(full: str) -> Tuple[List[Dict[str, Any]], int, int]:
    """Read one segment: ``(records, good_bytes, torn_bytes)`` —
    scanning stops at the first incomplete or CRC-failing frame."""
    records: List[Dict[str, Any]] = []
    good = 0
    size = os.path.getsize(full)
    with open(full, "rb") as f:
        while True:
            hdr = f.read(_FRAME.size)
            if len(hdr) < _FRAME.size:
                break
            ln, crc = _FRAME.unpack(hdr)
            if ln > _MAX_RECORD:
                break
            payload = f.read(ln)
            if len(payload) < ln or zlib.crc32(payload) != crc:
                break
            try:
                rec = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                break
            if not isinstance(rec, dict):
                break
            records.append(rec)
            good += _FRAME.size + ln
    return records, good, size - good


def scan_journal(path: str, *,
                 repair: bool = False
                 ) -> Tuple[List[Dict[str, Any]], int]:
    """Read every complete record from the journal at ``path``,
    oldest first: ``(records, truncated_bytes)``. Scanning stops at
    the first bad CRC / torn frame — everything after it (including
    whole later segments) is counted as truncated. With
    ``repair=True`` the torn segment is physically truncated at the
    last complete record and later segments are removed, so a
    subsequent append continues from a clean tail."""
    if not os.path.isdir(path):
        raise JournalError(f"no journal directory at {path}")
    records: List[Dict[str, Any]] = []
    truncated = 0
    torn_at: Optional[int] = None
    for pos, (idx, name) in enumerate(_segments(path)):
        full = os.path.join(path, name)
        if torn_at is not None:
            # everything past the first torn frame is suspect: a later
            # segment could replay state the lost records invalidated
            truncated += os.path.getsize(full)
            if repair:
                os.unlink(full)
            continue
        recs, good, torn = _scan_file(full)
        records.extend(recs)
        if torn:
            truncated += torn
            torn_at = pos
            if repair:
                with open(full, "r+b") as f:
                    f.truncate(good)
    return records, truncated


class Journal:
    """Segmented CRC-framed append-only write-ahead log.

    >>> j = Journal("state/journal", fsync="batch")
    >>> sched = Scheduler(engine, journal=j)

    Opening an existing journal repairs its torn tail (see
    :func:`scan_journal`) and continues appending; ``truncated_bytes``
    reports what the repair dropped. ``segment_bytes`` bounds one
    segment file — rotation seals the current segment (flush + fsync +
    manifest rewrite through :func:`apex_tpu._atomic.atomic_write`)
    and opens the next. ``compact_min_finished`` arms automatic
    compaction: once that many ``finish`` records accumulate,
    :meth:`maybe_compact` (called by the scheduler at fetch
    boundaries) rewrites the live state — registrations plus
    unfinished requests with their merged emitted prefixes — into one
    fresh segment and drops everything finished. ``None`` leaves
    compaction manual (:meth:`compact`)."""

    def __init__(self, path: str, *, fsync: str = "batch",
                 segment_bytes: int = 4 * 1024 * 1024,
                 compact_min_finished: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy {fsync!r} not in {FSYNC_POLICIES}")
        if segment_bytes < 4096:
            raise ValueError(
                f"segment_bytes {segment_bytes} < 4096 — rotation "
                f"per record would thrash the manifest")
        self.path = os.path.abspath(path)
        self.fsync_policy = fsync
        self.segment_bytes = int(segment_bytes)
        self.compact_min_finished = compact_min_finished
        self.clock = clock
        os.makedirs(self.path, exist_ok=True)
        records, self.truncated_bytes = scan_journal(self.path,
                                                     repair=True)
        for rec in records:
            if rec.get("kind") == "meta" and int(
                    rec.get("format", 0)) > FORMAT_VERSION:
                raise JournalError(
                    f"journal format {rec['format']} is newer than "
                    f"this build's {FORMAT_VERSION}")
        self._seq = max((int(r.get("seq", 0)) for r in records),
                        default=0)
        #: counters (monotonic; the scheduler mirrors them into
        #: registry metrics and ``summary()``)
        self.appends = 0
        self.rotations = 0
        self.compactions = 0
        self.compaction_errors = 0
        self.fsyncs = 0
        self.fsync_s = 0.0
        self.last_append_bytes = 0
        #: ``(segment_name, records, bytes)`` of the most recently
        #: sealed segment — the journal_rotate event payload
        self.last_sealed: Optional[Tuple[str, int, int]] = None
        self._lag_bytes = 0
        self._finished_since_compact = 0
        segs = _segments(self.path)
        self._bytes_other = sum(
            os.path.getsize(os.path.join(self.path, n))
            for _, n in segs[:-1])
        if segs:
            self._segment_index = segs[-1][0]
            cur = os.path.join(self.path, segs[-1][1])
            self._segment_written = os.path.getsize(cur)
            self._segment_records = 0
            self._f = open(cur, "ab")
        else:
            self._segment_index = 1
            self._segment_written = 0
            self._segment_records = 0
            self._f = open(self._current_path(), "ab")
            self._write_manifest()

    # -- paths ---------------------------------------------------------------

    def _current_path(self) -> str:
        return os.path.join(self.path, _seg_name(self._segment_index))

    def segments(self) -> List[str]:
        """Segment filenames, oldest first."""
        return [n for _, n in _segments(self.path)]

    @property
    def seq(self) -> int:
        """Sequence number of the newest record (0 = empty)."""
        return self._seq

    @property
    def lag_bytes(self) -> int:
        """Bytes appended since the last fsync — the durability lag
        a crash right now could lose (page-cache resident)."""
        return self._lag_bytes

    def bytes_on_disk(self) -> int:
        """Total journal bytes across all segments."""
        return self._bytes_other + self._segment_written

    # -- appending -----------------------------------------------------------

    def append(self, kind: str, **fields: Any) -> int:
        """Append one record; returns its sequence number. Durability
        is the fsync policy's: ``always`` syncs here, ``batch`` at the
        next :meth:`commit`, ``none`` never."""
        if self._f is None:
            raise JournalError("journal is closed")
        self._seq += 1
        rec = {"seq": self._seq, "kind": kind}
        rec.update(fields)
        frame = _frame(_encode(rec))
        self._f.write(frame)
        n = len(frame)
        self.appends += 1
        self.last_append_bytes = n
        self._segment_written += n
        self._segment_records += 1
        self._lag_bytes += n
        if kind == "finish":
            self._finished_since_compact += 1
        if self.fsync_policy == "always":
            self._do_fsync()
        if self._segment_written >= self.segment_bytes:
            self.rotate()
        return self._seq

    def commit(self) -> None:
        """The batch-boundary durability point (the scheduler calls
        this once per fetch): flush buffered frames to the OS, and
        fsync under the ``batch`` policy."""
        if self._f is None or self._lag_bytes == 0:
            return
        if self.fsync_policy == "batch":
            self._do_fsync()
        else:
            self._f.flush()
            if self.fsync_policy == "none":
                # flushed to the page cache; a crash may lose it but a
                # clean reader (compaction, a scanner) sees everything
                self._lag_bytes = 0

    def _do_fsync(self) -> None:
        t0 = self.clock()
        self._f.flush()
        os.fsync(self._f.fileno())
        self.fsync_s += max(self.clock() - t0, 0.0)
        self.fsyncs += 1
        self._lag_bytes = 0

    def rotate(self) -> None:
        """Seal the current segment (flush + fsync + manifest rewrite
        through the shared atomic helper) and open the next."""
        if self._f is None:
            raise JournalError("journal is closed")
        self._do_fsync()
        self._f.close()
        self.last_sealed = (_seg_name(self._segment_index),
                            self._segment_records,
                            self._segment_written)
        self._bytes_other += self._segment_written
        self._segment_index += 1
        self._segment_written = 0
        self._segment_records = 0
        self._f = open(self._current_path(), "ab")
        self.rotations += 1
        self._write_manifest()

    def _write_manifest(self) -> None:
        segs = self.segments()
        cur = _seg_name(self._segment_index)
        manifest = {
            "format": FORMAT_VERSION,
            "current": cur,
            "sealed": [n for n in segs if n != cur],
        }
        _atomic.atomic_write(
            os.path.join(self.path, _MANIFEST),
            lambda f: json.dump(manifest, f, indent=1, sort_keys=True),
            text=True)

    # -- compaction ----------------------------------------------------------

    def maybe_compact(self) -> bool:
        """Compact when the armed threshold of finished requests has
        accumulated (no-op when ``compact_min_finished`` is None).
        A failed rewrite (ENOSPC is likely precisely when compacting)
        degrades to a counted error rather than raising: the tail
        segment stays open for appends (see :meth:`compact`), so a
        disk hiccup at a fetch boundary never becomes a serving
        outage; the threshold re-arms after another
        ``compact_min_finished`` finishes."""
        if (self.compact_min_finished is None
                or self._finished_since_compact
                < self.compact_min_finished):
            return False
        try:
            self.compact()
        except OSError:
            self.compaction_errors += 1
            self._finished_since_compact = 0
            return False
        return True

    def compact(self) -> Dict[str, int]:
        """Rewrite the journal's LIVE state into one fresh segment —
        meta, registrations, and every unfinished request as a single
        ``submit`` + merged full-prefix ``extend`` (+ ``park``) — and
        drop finished requests. The new segment is materialised
        through :func:`apex_tpu._atomic.atomic_write` (complete or
        absent, fsynced along with its directory entry) BEFORE old
        segments are removed, and extends carry absolute offsets, so
        a crash anywhere in between replays to the same state. If the
        rewrite itself fails (ENOSPC), the previous tail segment is
        reopened for append and the error re-raised — a failed
        compaction leaves a journal that still journals."""
        if self._f is None:
            raise JournalError("journal is closed")
        self._f.flush()
        records, _ = scan_journal(self.path)
        state = replay_state(records)
        out: List[Dict[str, Any]] = []
        meta = dict(state.meta) if state.meta else {
            "kind": "meta", "format": FORMAT_VERSION}
        out.append(meta)
        out.extend(dict(a) for a in state.adapters)
        out.extend({"kind": "prefix", "tokens": list(t)}
                   for t in state.prefixes)
        dropped = 0
        for rq in state.requests.values():
            if rq["finished"]:
                dropped += 1
                continue
            sub = {k: rq[k] for k in _SUBMIT_FIELDS if k in rq}
            sub["kind"] = "submit"
            out.append(sub)
            if rq["emitted"]:
                out.append({"kind": "extend",
                            "request_id": rq["request_id"], "start": 0,
                            "tokens": list(rq["emitted"]),
                            "logprobs": list(rq["logprobs"])})
            if rq["parked"]:
                out.append({"kind": "park",
                            "request_id": rq["request_id"]})
        for i, rec in enumerate(out):
            rec["seq"] = i + 1
        old = [os.path.join(self.path, n) for n in self.segments()]
        self._f.close()
        self._f = None
        self._segment_index += 1
        new_path = self._current_path()

        def _write(f):
            for rec in out:
                f.write(_frame(_encode(rec)))

        try:
            # atomic_write fsyncs the segment AND its directory entry
            # before returning, so the unlinks below can never outlive
            # the new segment across a power loss
            _atomic.atomic_write(new_path, _write)
        except BaseException:
            # rewrite failed mid-compaction: reopen the previous tail
            # for append so the scheduler's _jlog keeps working — the
            # old segments are all still intact
            self._segment_index -= 1
            self._f = open(old[-1], "ab")
            self._segment_written = os.path.getsize(old[-1])
            raise
        removed = 0
        try:
            for p in old:
                os.unlink(p)
                removed += 1
        finally:
            # even a failed unlink leaves a valid journal (replay is
            # idempotent over leftover old segments) — appends must
            # continue on the compacted tail regardless
            self._f = open(new_path, "ab")
            self._seq = max(self._seq, len(out))
            self._segment_written = os.path.getsize(new_path)
            self._segment_records = len(out)
            self._bytes_other = sum(
                os.path.getsize(os.path.join(self.path, n))
                for _, n in _segments(self.path)
                if os.path.join(self.path, n) != new_path)
            self._lag_bytes = 0
            self.compactions += 1
            self._finished_since_compact = 0
            self._write_manifest()
        return {"records": len(out), "dropped_finished": dropped,
                "segments_removed": removed}

    # -- lifecycle -----------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """Flat counters for ``summary()`` / the bench line."""
        return {
            "appends": float(self.appends),
            "bytes": float(self.bytes_on_disk()),
            "lag_bytes": float(self._lag_bytes),
            "fsyncs": float(self.fsyncs),
            "fsync_s": self.fsync_s,
            "rotations": float(self.rotations),
            "compactions": float(self.compactions),
            "compaction_errors": float(self.compaction_errors),
            "segments": float(len(self.segments())),
            "truncated_bytes": float(self.truncated_bytes),
        }

    def close(self) -> None:
        """Flush, fsync (unless policy ``none``), and close."""
        if self._f is None:
            return
        if self.fsync_policy == "none":
            self._f.flush()
        else:
            self._do_fsync()
        self._f.close()
        self._f = None

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- replaying ---------------------------------------------------------------

#: the submit-record fields recovery rebuilds a Request from (also the
#: compaction rewrite's projection)
_SUBMIT_FIELDS = (
    "order", "request_id", "prompt", "max_tokens", "temperature",
    "top_k", "top_p", "seed", "eos_token_id", "stop", "constrained",
    "deadline_remaining", "tenant", "adapter", "adapter_name",
)


@dataclasses.dataclass
class JournalState:
    """The journal's replayed state: what was registered, and every
    request with its merged emitted prefix and lifecycle flags."""

    meta: Optional[Dict[str, Any]] = None
    #: adapter records in first-registration order (name-deduped)
    adapters: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    #: prefix token lists in first-registration order (deduped)
    prefixes: List[List[int]] = dataclasses.field(default_factory=list)
    #: request_id → submit fields + ``emitted``/``logprobs``/
    #: ``parked``/``finished``/``finish_reason``
    requests: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    #: extend records whose start offset did not splice (gap after a
    #: mid-journal truncation) — surfaced, never silently dropped
    anomalies: int = 0

    def unfinished(self) -> List[Dict[str, Any]]:
        """Requests recovery must resubmit, in original submit
        order."""
        live = [r for r in self.requests.values()
                if not r["finished"]]
        live.sort(key=lambda r: r.get("order", 0))
        return live


def replay_state(records: List[Dict[str, Any]]) -> JournalState:
    """Fold scanned records into a :class:`JournalState`. Replay is
    idempotent over duplicated suffixes (absolute extend offsets,
    name-keyed registrations), which is what makes compaction
    crash-safe."""
    st = JournalState()
    seen_adapters: Dict[str, int] = {}
    seen_prefixes: Dict[Tuple[int, ...], int] = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "meta":
            st.meta = rec
        elif kind == "adapter":
            name = rec.get("name")
            pos = seen_adapters.get(name)
            if pos is None:
                seen_adapters[name] = len(st.adapters)
                st.adapters.append(rec)
            else:
                # a post-recovery re-registration of the same name
                # carries the FRESH engine's id — keep the LATEST
                # record (first-seen order preserved) so the id map
                # matches the latest generation of submit records
                st.adapters[pos] = rec
        elif kind == "prefix":
            key = tuple(int(t) for t in rec.get("tokens", ()))
            if key not in seen_prefixes:
                seen_prefixes[key] = len(st.prefixes)
                st.prefixes.append(list(key))
        elif kind == "submit":
            rid = rec.get("request_id")
            rq = st.requests.get(rid)
            if rq is None:
                rq = st.requests[rid] = {"emitted": [], "logprobs": []}
            for k in _SUBMIT_FIELDS:
                if k in rec:
                    rq[k] = rec[k]
            rq["parked"] = False
            rq["finished"] = False
            rq["finish_reason"] = None
        elif kind == "extend":
            rq = st.requests.get(rec.get("request_id"))
            if rq is None:
                st.anomalies += 1
                continue
            start = int(rec.get("start", 0))
            toks = [int(t) for t in rec.get("tokens", ())]
            lps = list(rec.get("logprobs", ()))
            if start > len(rq["emitted"]):
                st.anomalies += 1
                continue
            rq["emitted"][start:start + len(toks)] = toks
            rq["logprobs"][start:start + len(lps)] = lps
        elif kind == "finish":
            rq = st.requests.get(rec.get("request_id"))
            if rq is not None:
                rq["finished"] = True
                rq["finish_reason"] = rec.get("reason")
                rq["parked"] = False
        elif kind == "park":
            rq = st.requests.get(rec.get("request_id"))
            if rq is not None:
                rq["parked"] = True
        elif kind == "resume":
            rq = st.requests.get(rec.get("request_id"))
            if rq is not None:
                rq["parked"] = False
    return st


@dataclasses.dataclass
class RecoveryReport:
    """What a recovery replayed — the ``recover`` flight event's
    payload and the drill's acceptance evidence."""

    requests: int = 0
    adapters: int = 0
    prefixes: int = 0
    skipped_constrained: int = 0
    #: adapter REGISTRATIONS that could not replay (explicit weights,
    #: ``seed: null`` — not re-derivable)
    skipped_adapters: int = 0
    #: REQUESTS skipped because their journaled adapter id could not
    #: be mapped onto the fresh engine (pinned to an unreplayable
    #: adapter, or the registration record itself was lost to a torn
    #: tail) — running them with guessed weights would violate the
    #: bit-identical contract
    skipped_adapter_requests: int = 0
    truncated_bytes: int = 0
    anomalies: int = 0
    wall_s: float = 0.0

    def as_dict(self) -> Dict[str, float]:
        return {f.name: float(getattr(self, f.name))
                for f in dataclasses.fields(self)}


def _engine_spec(engine) -> Dict[str, Any]:
    """The describe() subset a journal pins engine compatibility on —
    round-tripped through the journal's own JSON encoding so both
    sides of the comparison normalise identically."""
    desc = engine.describe()
    spec = {k: desc[k] for k in ("model", "engine", "tp")}
    return json.loads(_encode(spec).decode("utf-8"))


def replay_into(scheduler, source, *,
                truncated_bytes: int = 0) -> RecoveryReport:
    """Replay a journal's live state into ``scheduler``: re-register
    seeded adapters and pooled prefixes (idempotent — registering an
    existing name/prefix returns the existing id), then re-submit
    every unfinished request through the PR-12
    ``submit(replay_prefix=)`` hook so its stream continues
    bit-identically. ``source`` is a journal directory path or an
    already-scanned record list. Deadlines re-base at the scheduler's
    current clock from the journaled remaining budget. Constrained
    requests (opaque DFA — not serialisable) and requests pinned to an
    explicit-weights adapter (``seed: null`` — not re-derivable) are
    skipped with counted stats.

    Engine adapter ids are assigned sequentially at registration, so
    the fresh engine's ids need not match the journaled ones (any
    skipped ``seed: null`` registration shifts every later id — and
    across a SECOND recovery a re-registration can even reuse a dead
    registration's old id): each request maps back to its adapter by
    NAME (the stable, engine-deduped cross-recovery key its submit
    record carries), falling back to a journaled-id → fresh-id map
    for hand-built records, and a request whose adapter cannot be
    mapped is skipped with a counted stat — never resubmitted against
    whatever adapter happens to occupy the journaled row."""
    if isinstance(source, str):
        records, truncated_bytes = scan_journal(source)
    else:
        records = source
    state = replay_state(records)
    report = RecoveryReport(truncated_bytes=truncated_bytes,
                            anomalies=state.anomalies)
    adapter_ids = {0: 0}        # base weights map to base weights
    adapter_names: Dict[str, int] = {}
    for ad in state.adapters:
        if ad.get("seed") is None:
            report.skipped_adapters += 1
            continue
        aid = int(scheduler.register_adapter(name=ad.get("name"),
                                             seed=int(ad["seed"])))
        if ad.get("name") is not None:
            adapter_names[ad["name"]] = aid
        jid = ad.get("adapter_id")
        if jid is not None:
            adapter_ids[int(jid)] = aid
        report.adapters += 1
    for toks in state.prefixes:
        scheduler.register_prefix(toks)
        report.prefixes += 1
    from apex_tpu.serving.request import Request, SamplingParams
    now = scheduler.clock()
    for rq in state.unfinished():
        if rq.get("constrained"):
            report.skipped_constrained += 1
            continue
        aname = rq.get("adapter_name")
        adapter = (adapter_names.get(aname) if aname is not None
                   else adapter_ids.get(int(rq.get("adapter") or 0)))
        if adapter is None:
            report.skipped_adapter_requests += 1
            continue
        remaining = rq.get("deadline_remaining")
        req = Request(
            request_id=rq["request_id"],
            prompt=list(rq["prompt"]),
            max_tokens=int(rq["max_tokens"]),
            sampling=SamplingParams(
                temperature=rq.get("temperature", 0.0),
                top_k=rq.get("top_k", 0),
                top_p=rq.get("top_p", 1.0),
                seed=rq.get("seed")),
            eos_token_id=rq.get("eos_token_id"),
            deadline=(None if remaining is None
                      else now + float(remaining)),
            stop=rq.get("stop"),
            tenant=rq.get("tenant") or "default",
            adapter=adapter)
        # an empty replay prefix is still a failover hand-off (list,
        # not None): the original submit already charged the tenant's
        # token budget — recovery must not double-bill or throttle it
        scheduler.submit(req, replay_prefix=list(rq["emitted"]),
                         replay_logprobs=list(rq["logprobs"]))
        report.requests += 1
    scheduler._journal_recovered += report.requests
    if scheduler.recorder is not None:
        scheduler.recorder.record(
            "recover", report.requests, report.adapters,
            report.prefixes, report.truncated_bytes)
    if scheduler.telemetry is not None:
        scheduler.telemetry.journal_recovered.inc(report.requests)
    return report


def recover_scheduler(journal_dir: str, engine_factory,
                      *, fsync: str = "batch",
                      segment_bytes: int = 4 * 1024 * 1024,
                      compact_min_finished: Optional[int] = None,
                      strict: bool = True,
                      **scheduler_kwargs) -> Tuple[Any, RecoveryReport]:
    """Crash-safe warm restart: rebuild a fresh engine + scheduler
    from the journal at ``journal_dir`` and return
    ``(scheduler, report)``. The journal's torn tail is repaired, the
    factory engine is warmed and (with ``strict=True``) checked
    against the journaled engine spec (:meth:`Engine.describe`
    round-trip — an incompatible engine would silently decode
    different streams), the journal is re-opened for continued
    appends, and :func:`replay_into` resubmits every unfinished
    request. The recovered scheduler journals its own resubmissions,
    so a second crash recovers from the same directory."""
    t0 = time.monotonic()
    records, truncated = scan_journal(journal_dir, repair=True)
    state = replay_state(records)
    engine = engine_factory()
    engine.warmup()     # idempotent; adapters register post-warmup
    if strict and state.meta is not None \
            and state.meta.get("engine_spec") is not None:
        want = state.meta["engine_spec"]
        have = _engine_spec(engine)
        if want != have:
            diff = sorted(k for k in set(want) | set(have)
                          if want.get(k) != have.get(k))
            raise JournalError(
                f"engine_factory built an incompatible engine "
                f"(differs at {diff}) — a recovered stream would not "
                f"be bit-identical; pass strict=False to override")
    journal = Journal(journal_dir, fsync=fsync,
                      segment_bytes=segment_bytes,
                      compact_min_finished=compact_min_finished)
    from apex_tpu.serving.scheduler import Scheduler
    sched = Scheduler(engine, journal=journal, **scheduler_kwargs)
    report = replay_into(sched, records, truncated_bytes=truncated)
    report.wall_s = time.monotonic() - t0
    return sched, report
