"""Multi-tenant serving policy: weighted-fair queueing + rate limits.

"Millions of users" means tenants, not just requests: many fine-tunes
and traffic classes sharing ONE engine, with fairness under contention.
This module is the host-side half of the tenancy subsystem — pure
stdlib, no jax (the device half is the batched multi-LoRA adapter pool
in :mod:`apex_tpu.models.gpt` / the engine's ``adapter_slots``):

- :class:`TenancyConfig` — per-tenant weights, token-budget rate
  limits, and the priority-aging knob.
- :class:`TenantBook` — the scheduler's per-tenant bookkeeping:

  * **Weighted-fair queueing with deficit counters.** Each tenant
    carries a *normalized-service* counter (served tokens divided by
    its weight — the deficit-counter spelling: the LOWEST counter is
    the tenant most behind its fair share). Admission picks the
    backlogged tenant with the smallest counter, so under sustained
    contention per-tenant served-token shares converge to the weight
    ratio — the classic start-time-fair-queueing argument, charged on
    ACTUAL emitted tokens rather than request counts so long and short
    streams settle to the same token shares.
  * **Priority aging.** The selection key subtracts
    ``aging_per_s × head-of-line wait``: a tenant starved by heavier
    competitors accumulates priority linearly with queue time and is
    eventually served regardless of its weight — no starvation, by
    construction.
  * **Token-budget rate limits.** Per-tenant token buckets (capacity
    ``rate × burst_s``, refilled continuously) charged the request's
    ``max_tokens`` at submit; an empty bucket rejects with
    :class:`TenantThrottled` carrying ``retry_after_s`` — the time the
    bucket needs to refill the request's charge — which the API layer
    maps to 429 + ``Retry-After`` (the PR-5/PR-6 overload path).
  * **Accounting.** Per-tenant submitted/admitted/shed/throttled/token
    counters — the ``serving_tenant_*`` metric and ``summary()``
    source.

The book is deliberately queue-agnostic: the scheduler keeps its one
arrival-order deque (every recovery/eviction/expiry path is untouched)
and only the *pop order* consults :meth:`TenantBook.pick`. A
single-tenant workload therefore pops strict FIFO — bit-identical
scheduling to the pre-tenancy engine.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional


class TenantThrottled(RuntimeError):
    """Per-tenant rate-limit rejection at submit. Deliberately NOT a
    :class:`~apex_tpu.serving.scheduler.QueueFull`: queue pressure is
    replica-local (a fleet router may retry elsewhere), a tenant's
    token budget is not — the rejection must propagate to the client
    as a 429 + ``Retry-After`` without another replica being tried.
    ``retry_after_s`` is when the tenant's bucket will have refilled
    this request's charge."""

    def __init__(self, message: str, *, tenant: str,
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


#: the tenant every request without an identity belongs to — one
#: spelling shared by Request, the scheduler, and the API layer
DEFAULT_TENANT = "default"

#: the shared identity unseen tenants fold into once the book is
#: tracking ``TenancyConfig.max_tenants`` distinct ids — caps host
#: state against unauthenticated per-request-unique tenant strings
OVERFLOW_TENANT = "overflow"


@dataclasses.dataclass(frozen=True)
class TenancyConfig:
    """Per-tenant serving policy (static, host-only).

    ``weights`` maps tenant id → fair-share weight (unlisted tenants
    get ``default_weight``); under contention served-token shares
    converge to the weight ratio. ``rates`` maps tenant id → sustained
    token budget (generated tokens per second; unlisted tenants get
    ``default_rate``, ``None`` = unlimited); a submit whose
    ``max_tokens`` charge exceeds the tenant's bucket raises
    :class:`TenantThrottled`. ``burst_s`` sizes the bucket
    (``rate × burst_s``, floored at one worst-case request so a legal
    request can always eventually pass). ``aging_per_s`` is the
    priority-aging slope: normalized-service units of credit per
    second a tenant's head request waits — 0 disables aging (pure
    WFQ; a zero-weight-ish tenant could then starve)."""

    weights: Mapping[str, float] = dataclasses.field(
        default_factory=dict)
    default_weight: float = 1.0
    rates: Mapping[str, Optional[float]] = dataclasses.field(
        default_factory=dict)
    default_rate: Optional[float] = None
    burst_s: float = 2.0
    aging_per_s: float = 1.0

    #: distinct tenant identities the book tracks before folding new
    #: ones into the shared overflow tenant — tenant ids arrive from
    #: UNAUTHENTICATED request fields (the X-Tenant-Id header, the
    #: OpenAI ``user`` string), and unbounded ids would grow
    #: per-tenant state and labeled metric children without limit in
    #: a long-running server. Configured tenants (weights/rates keys)
    #: always get their own identity.
    max_tenants: int = 4096

    def __post_init__(self):
        if self.max_tenants < 1:
            raise ValueError(
                f"max_tenants {self.max_tenants} must be >= 1")
        for t, w in dict(self.weights).items():
            if not w > 0.0:
                raise ValueError(
                    f"tenant {t!r} weight {w} must be > 0 (a zero "
                    f"weight is an infinite deficit — use a rate "
                    f"limit to cap a tenant instead)")
        if not self.default_weight > 0.0:
            raise ValueError(
                f"default_weight {self.default_weight} must be > 0")
        for t, r in dict(self.rates).items():
            if r is not None and not r > 0.0:
                raise ValueError(
                    f"tenant {t!r} rate {r} must be > 0 or None "
                    f"(unlimited)")
        if self.default_rate is not None and not self.default_rate > 0.0:
            raise ValueError(
                f"default_rate {self.default_rate} must be > 0 or None")
        if self.burst_s <= 0.0:
            raise ValueError(f"burst_s {self.burst_s} must be > 0")
        if self.aging_per_s < 0.0:
            raise ValueError(
                f"aging_per_s {self.aging_per_s} must be >= 0")


class _TenantStats:
    __slots__ = ("submitted", "admitted", "shed", "throttled", "tokens")

    def __init__(self):
        self.submitted = 0
        self.admitted = 0
        self.shed = 0
        self.throttled = 0
        self.tokens = 0


class TenantBook:
    """Per-tenant WFQ state + rate buckets + accounting (see module
    docstring). Host-only and deterministic in (clock, call sequence),
    so fault replay and the post-mortem bundle see the same decisions
    a live run made."""

    def __init__(self, cfg: Optional[TenancyConfig], clock):
        self.cfg = cfg or TenancyConfig()
        self.clock = clock
        #: normalized-service deficit counters: served tokens / weight
        #: per tenant — the WFQ selection key (lowest = most behind)
        self._service: Dict[str, float] = {}
        #: rate buckets: tenant -> [level_tokens, last_refill_ts]
        self._bucket: Dict[str, list] = {}
        self._stats: Dict[str, _TenantStats] = {}

    # -- config lookups ------------------------------------------------------

    def admit_tenant(self, tenant: str) -> str:
        """Resolve a request's tenant identity to the one the book
        tracks: known ids and configured ids (weights/rates keys) keep
        their identity; a NEW id past ``max_tenants`` distinct tracked
        tenants folds into :data:`OVERFLOW_TENANT` — per-tenant state
        and labeled metrics stay bounded whatever strings an
        unauthenticated client invents. The scheduler rewrites
        ``Request.tenant`` with the result so accounting, WFQ, and
        rate buckets all see one consistent identity."""
        if tenant in self._stats or tenant in self.cfg.weights \
                or tenant in self.cfg.rates:
            return tenant
        if len(self._stats) >= self.cfg.max_tenants:
            return OVERFLOW_TENANT
        return tenant

    def weight(self, tenant: str) -> float:
        return float(self.cfg.weights.get(tenant,
                                          self.cfg.default_weight))

    def rate(self, tenant: str) -> Optional[float]:
        r = self.cfg.rates.get(tenant, self.cfg.default_rate)
        return None if r is None else float(r)

    def stats(self, tenant: str) -> _TenantStats:
        st = self._stats.get(tenant)
        if st is None:
            st = self._stats[tenant] = _TenantStats()
        return st

    @property
    def tenants_seen(self):
        return sorted(self._stats)

    # -- weighted-fair queueing ----------------------------------------------

    def note_backlogged(self, tenant: str) -> None:
        """First sight of a tenant in the backlog: start its deficit
        counter at the MINIMUM of the live counters (the virtual-clock
        clamp) — a newcomer competes from "now", it does not get
        credit for every token served before it existed."""
        if tenant not in self._service:
            floor = min(self._service.values(), default=0.0)
            self._service[tenant] = floor

    def rejoin(self, tenant: str, floor: float) -> None:
        """A tenant RE-ENTERING the backlog after going idle clamps up
        to ``floor`` (the minimum counter among currently-backlogged
        tenants — the scheduler computes it, since only it knows who
        is backlogged): idle time is not banked service credit, so a
        returning tenant competes from "now" instead of monopolizing
        the engine until its stale counter catches up on everything
        served while it was away."""
        self._service[tenant] = max(self._service.get(tenant, floor),
                                    floor)

    def on_tokens(self, tenant: str, n: int) -> None:
        """Charge ``n`` served tokens to ``tenant``'s deficit counter
        (normalized by weight) — called per emitted token batch, so
        fairness settles on ACTUAL service, not on admission-time
        estimates."""
        if n <= 0:
            return
        self.note_backlogged(tenant)
        self._service[tenant] = (self._service.get(tenant, 0.0)
                                 + n / self.weight(tenant))
        self.stats(tenant).tokens += n

    def pick(self, head_wait: Mapping[str, float]) -> str:
        """The WFQ decision: among backlogged tenants (``head_wait``
        maps tenant → seconds its head-of-line request has queued),
        pick the one most behind its fair share — smallest
        ``deficit - aging_per_s × wait``. Aging makes the key strictly
        decrease with queue time, so every tenant is eventually
        chosen: no starvation. Deterministic tie-break on (wait desc,
        name) so replays reproduce the order."""
        if not head_wait:
            raise ValueError("pick() needs at least one tenant")
        aging = self.cfg.aging_per_s
        for t in head_wait:
            self.note_backlogged(t)
        return min(
            head_wait,
            key=lambda t: (self._service[t] - aging * head_wait[t],
                           -head_wait[t], t))

    def service_of(self, tenant: str) -> float:
        return self._service.get(tenant, 0.0)

    def pick_victim(self, service: Mapping[str, float]) -> str:
        """:meth:`pick` mirrored for preemption: among tenants holding
        active slots (``service`` maps tenant → its deficit counter,
        snapshotted by the scheduler so the ``preempt`` flight event
        carries the exact decision inputs), evict from the one
        furthest AHEAD of its fair share — the largest counter.
        Deterministic tie-break on name, so a post-mortem replay
        (``telemetry.replay.replay_preemptions``) re-derives the same
        victim from the recorded candidates."""
        if not service:
            raise ValueError("pick_victim() needs at least one tenant")
        return max(sorted(service), key=lambda t: service[t])

    # -- token-budget rate limits --------------------------------------------

    def _refill(self, tenant: str, rate: float, now: float) -> list:
        cap = rate * self.cfg.burst_s
        b = self._bucket.get(tenant)
        if b is None:
            b = self._bucket[tenant] = [cap, now]
        level, last = b
        b[0] = min(cap, level + rate * max(now - last, 0.0))
        b[1] = now
        return b

    def throttle(self, tenant: str, max_tokens: int,
                 now: Optional[float] = None) -> Optional[float]:
        """Charge ``max_tokens`` to ``tenant``'s bucket. Returns None
        when the charge fits (bucket debited); else the seconds until
        it would (the 429's ``Retry-After``), leaving the bucket
        untouched. The effective charge is clamped to the bucket
        capacity so a single over-burst request is gated, not
        permanently unservable."""
        rate = self.rate(tenant)
        if rate is None:
            return None
        now = self.clock() if now is None else now
        b = self._refill(tenant, rate, now)
        need = min(float(max_tokens), rate * self.cfg.burst_s)
        if b[0] >= need:
            b[0] -= need
            return None
        return (need - b[0]) / rate

    def bucket_level(self, tenant: str) -> Optional[float]:
        """Current bucket level (refreshed; None = unlimited)."""
        rate = self.rate(tenant)
        if rate is None:
            return None
        return self._refill(tenant, rate, self.clock())[0]

    # -- reporting -----------------------------------------------------------

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant accounting snapshot: submitted/admitted/shed/
        throttled/tokens plus the live deficit counter and weight."""
        out: Dict[str, Dict[str, float]] = {}
        for t in sorted(self._stats):
            st = self._stats[t]
            out[t] = {
                "weight": self.weight(t),
                "submitted": float(st.submitted),
                "admitted": float(st.admitted),
                "shed": float(st.shed),
                "throttled": float(st.throttled),
                "tokens": float(st.tokens),
                "deficit": self.service_of(t),
            }
        return out
