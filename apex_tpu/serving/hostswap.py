"""Host-RAM page tier under the device pool — swap policy.

The paged KV cache (serving/pages.py) hard-caps conversations per chip
at the HBM page pool: an idle conversation squats on its private pages
until it finishes. This module owns the host side of oversubscription:
an LRU over PARKED conversations whose pages have been gathered out of
the device pool (compiled ``pages_out`` gather, one variant per
swap-batch rung) into host buffers, so a paused stream costs host RAM
while active streams keep every HBM page. Resume scatters the payload
back (``pages_in``) or — when the scheduler prices replay cheaper —
recomputes from the grow-only emitted-prefix snapshot and the payload
is simply dropped.

Three deliberately host-only pieces live here:

- :func:`swap_rungs` / :func:`plan_rungs` — the static swap-batch
  geometry. A slot's private page count varies per conversation, but
  every compiled gather/scatter variant must have a static page count;
  power-of-two rungs plus binary decomposition (``5 -> 4 + 1``) cover
  any count in at most ``log2(max_pages) + 1`` program calls, and the
  rung set is config-derived (``ceil(max_seq_len / page_size)``) so
  warmup can compile every variant up front.
- :class:`LRUIndex` — a bare recency-ordered set. The page tier uses
  it for park-order eviction; the engine reuses the SAME mechanism for
  LoRA adapter residency (cold adapter rows spill to host, the static
  device pool stops capping ``register_adapter``).
- :class:`HostPageTier` — the parked-entry store: opaque payloads
  keyed by request id with page/byte accounting and optional capacity
  eviction. Payloads are whatever the engine gathered (storage-form
  page blocks + the slot's state row), the tier never inspects them —
  and holds no arrays of its own, so every device-side shape stays
  config-derived (the HOST-TIER-STATIC lint rule polices the mirrors).

Everything here is O(1)/O(k) host bookkeeping; the device round-trip
(gather/scatter programs, donation discipline, warmup coverage) is the
engine's.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Tuple


def swap_rungs(max_pages: int) -> Tuple[int, ...]:
    """The compiled swap-batch sizes for a pool whose slots hold at
    most ``max_pages`` private pages: every power of two up to
    ``max_pages`` — enough that :func:`plan_rungs` can decompose any
    count ``1 .. max_pages`` exactly (binary representation), so no
    padding pages ever travel."""
    if max_pages < 1:
        raise ValueError(f"max_pages {max_pages} must be >= 1")
    rungs: List[int] = []
    r = 1
    while r <= max_pages:
        rungs.append(r)
        r *= 2
    return tuple(rungs)


def plan_rungs(n: int) -> List[int]:
    """Split a swap of ``n`` pages into compiled-rung calls, largest
    first: ``5 -> [4, 1]``. Exact (sum equals ``n``), deterministic,
    and every element is in ``swap_rungs(m)`` for any ``m >= n``."""
    if n < 0:
        raise ValueError(f"cannot swap {n} pages")
    out: List[int] = []
    bit = 1 << max(n.bit_length() - 1, 0)
    while bit:
        if n & bit:
            out.append(bit)
        bit >>= 1
    return out


class LRUIndex:
    """A recency-ordered set of keys — the one LRU mechanism shared by
    the page tier (park-order eviction) and the engine's adapter
    paging (cold-row spill). ``touch`` inserts-or-refreshes at the
    most-recent end; ``pop_coldest`` evicts from the least-recent end,
    skipping pinned keys."""

    __slots__ = ("_order",)

    def __init__(self) -> None:
        self._order: "OrderedDict[Any, None]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._order)

    def __contains__(self, key: Any) -> bool:
        return key in self._order

    def __iter__(self) -> Iterator[Any]:
        """Coldest (least recently touched) first."""
        return iter(self._order)

    def touch(self, key: Any) -> None:
        self._order[key] = None
        self._order.move_to_end(key)

    def discard(self, key: Any) -> None:
        self._order.pop(key, None)

    def pop_coldest(self, pinned=()) -> Optional[Any]:
        """Remove and return the least-recently-touched key not in
        ``pinned``; ``None`` when every key is pinned (caller decides
        whether that is a hard error — it is for adapter paging when
        every resident row is bound to a live slot)."""
        for key in self._order:
            if key not in pinned:
                del self._order[key]
                return key
        return None


class ParkedEntry:
    """One parked conversation's host-side payload: whatever the
    engine gathered (storage-form page block(s) plus the slot's state
    row), with the page/byte accounting the gauges read."""

    __slots__ = ("payload", "n_pages", "nbytes")

    def __init__(self, payload: Any, n_pages: int, nbytes: int):
        self.payload = payload
        self.n_pages = n_pages
        self.nbytes = nbytes


class HostPageTier:
    """LRU store of parked conversations. ``capacity_pages`` bounds
    the host-RAM footprint in PAGES (0 = unbounded): parking past the
    bound evicts the coldest entries — eviction only drops the swap
    payload, never the conversation, because the scheduler always
    keeps the grow-only emitted-prefix snapshot and falls back to
    recompute-resume when ``take`` misses."""

    __slots__ = ("capacity_pages", "_entries", "_lru", "pages",
                 "bytes", "parks_total", "takes_total", "drops_total")

    def __init__(self, capacity_pages: int = 0):
        if capacity_pages < 0:
            raise ValueError(
                f"capacity_pages {capacity_pages} must be >= 0")
        self.capacity_pages = capacity_pages
        self._entries: Dict[Any, ParkedEntry] = {}
        self._lru = LRUIndex()
        self.pages = 0
        self.bytes = 0
        self.parks_total = 0
        self.takes_total = 0
        self.drops_total = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Any) -> bool:
        return key in self._entries

    def park(self, key: Any, payload: Any, n_pages: int,
             nbytes: int) -> List[Tuple[Any, ParkedEntry]]:
        """Store ``payload`` under ``key`` at the most-recent end and
        return the ``(key, entry)`` pairs evicted to stay under
        ``capacity_pages`` (possibly including the new entry itself
        when it alone exceeds the bound — the caller downgrades those
        to recompute-resume). Re-parking an existing key is a bug
        (the conversation would have to be resumed first)."""
        if key in self._entries:
            raise ValueError(f"{key!r} is already parked")
        self._entries[key] = ParkedEntry(payload, n_pages, nbytes)
        self._lru.touch(key)
        self.pages += n_pages
        self.bytes += nbytes
        self.parks_total += 1
        evicted: List[Tuple[Any, ParkedEntry]] = []
        while self.capacity_pages and self.pages > self.capacity_pages:
            cold = self._lru.pop_coldest()
            if cold is None:  # pragma: no cover - entries imply keys
                break
            ent = self._entries.pop(cold)
            self.pages -= ent.n_pages
            self.bytes -= ent.nbytes
            self.drops_total += 1
            evicted.append((cold, ent))
        return evicted

    def take(self, key: Any) -> Optional[ParkedEntry]:
        """Remove and return ``key``'s entry, or ``None`` when it was
        capacity-evicted (or never swap-parked) — the recompute
        fallback signal."""
        ent = self._entries.pop(key, None)
        if ent is None:
            return None
        self._lru.discard(key)
        self.pages -= ent.n_pages
        self.bytes -= ent.nbytes
        self.takes_total += 1
        return ent

    def touch(self, key: Any) -> None:
        """Refresh ``key``'s recency (a parked conversation the router
        expects to resume soon)."""
        if key in self._entries:
            self._lru.touch(key)

    def stats(self) -> Dict[str, float]:
        return {
            "parked_entries": float(len(self._entries)),
            "pages": float(self.pages),
            "bytes": float(self.bytes),
            "capacity_pages": float(self.capacity_pages),
            "parks_total": float(self.parks_total),
            "takes_total": float(self.takes_total),
            "drops_total": float(self.drops_total),
        }
