"""apex_tpu.serving — static-shape continuous-batching inference engine.

The training side of the repo compiles ONE program per step and never
recompiles; this package re-derives vLLM-style continuous batching under
the same discipline (the move ``schedules.py`` made for pipeline
parallelism): a fixed batch of ``B`` decode *slots* drives one compiled
per-token program, and when a slot finishes (eos / token budget /
deadline) the next queued request is admitted into it by prefilling its
prompt at a static padded length and inserting the resulting KV block
into the shared cache — per-slot position, budget, eos, and sampling
parameters are device arrays, so admission and decode are trace-stable
(zero compiled-program cache misses after warmup).

Layout:

- :mod:`apex_tpu.serving.request`   — Request / SamplingParams /
  Completion host-side dataclasses,
- :mod:`apex_tpu.serving.sampling`  — the one temperature/top-k/top-p
  sampler shared by ``gpt.generate`` (scalar params) and the engine
  (per-slot vectors),
- :mod:`apex_tpu.serving.engine`    — the device loop: slot state,
  compiled step/admit/retire programs,
- :mod:`apex_tpu.serving.scheduler` — the host loop: request queue with
  backpressure, deadlines, response stream, serving metrics,
- :mod:`apex_tpu.serving.api`       — OpenAI-compatible HTTP front end
  (stdlib-only): SSE streaming, stop sequences, logprobs, n>1,
  JSON-schema-constrained decoding.

``engine``/``scheduler`` import :mod:`apex_tpu.models.gpt`, which itself
imports :mod:`.sampling`; they are loaded lazily (PEP 562) so either
entry point — model first or serving first — resolves without a cycle.
"""

from __future__ import annotations

from apex_tpu.serving import request  # noqa: F401
from apex_tpu.serving.request import (  # noqa: F401
    Completion,
    Request,
    SamplingParams,
    StopMatcher,
    StreamEvent,
)

__all__ = [
    "request", "sampling", "engine", "scheduler", "resilience", "api",
    "pages", "fleet", "tuner", "tenancy", "journal",
    "Journal", "JournalError", "RecoveryReport",
    "recover_scheduler", "replay_into", "scan_journal",
    "TenancyConfig", "TenantBook", "TenantThrottled",
    "Request", "SamplingParams", "Completion", "StreamEvent",
    "StopMatcher",
    "Engine", "EngineConfig", "Scheduler", "QueueFull",
    "SpecGateConfig", "TunerConfig", "Controller",
    "Admission", "AdmitResult", "StepHandle",
    "ChunkedAdmission", "PageAllocator", "PagesExhausted",
    "FaultPlan", "FaultSpec", "FleetFaultPlan", "ResilienceConfig",
    "HealthMonitor", "EngineFault", "InjectedFault", "EngineFailed",
    "Router", "FleetConfig", "FleetHealth", "EvictedRequest",
]

# ``sampling`` (jax) and ``api`` load lazily alongside engine/scheduler
# so ``import apex_tpu.serving`` — and through it the stdlib-only
# ``apex_tpu.serving.api`` front end — never drags jax in eagerly (the
# api dependency-free test pins this).
_LAZY = {
    "sampling": "apex_tpu.serving.sampling",
    "api": "apex_tpu.serving.api",
    "engine": "apex_tpu.serving.engine",
    "scheduler": "apex_tpu.serving.scheduler",
    "resilience": "apex_tpu.serving.resilience",
    "pages": "apex_tpu.serving.pages",
    "Engine": "apex_tpu.serving.engine",
    "EngineConfig": "apex_tpu.serving.engine",
    "Admission": "apex_tpu.serving.engine",
    "AdmitResult": "apex_tpu.serving.engine",
    "ChunkedAdmission": "apex_tpu.serving.engine",
    "StepHandle": "apex_tpu.serving.engine",
    "PageAllocator": "apex_tpu.serving.pages",
    "PagesExhausted": "apex_tpu.serving.pages",
    "Scheduler": "apex_tpu.serving.scheduler",
    "QueueFull": "apex_tpu.serving.scheduler",
    "SpecGateConfig": "apex_tpu.serving.scheduler",
    "EvictedRequest": "apex_tpu.serving.scheduler",
    "tuner": "apex_tpu.serving.tuner",
    "TunerConfig": "apex_tpu.serving.tuner",
    "Controller": "apex_tpu.serving.tuner",
    "tenancy": "apex_tpu.serving.tenancy",
    "TenancyConfig": "apex_tpu.serving.tenancy",
    "TenantBook": "apex_tpu.serving.tenancy",
    "TenantThrottled": "apex_tpu.serving.tenancy",
    "journal": "apex_tpu.serving.journal",
    "Journal": "apex_tpu.serving.journal",
    "JournalError": "apex_tpu.serving.journal",
    "RecoveryReport": "apex_tpu.serving.journal",
    "recover_scheduler": "apex_tpu.serving.journal",
    "replay_into": "apex_tpu.serving.journal",
    "scan_journal": "apex_tpu.serving.journal",
    "fleet": "apex_tpu.serving.fleet",
    "Router": "apex_tpu.serving.fleet",
    "FleetConfig": "apex_tpu.serving.fleet",
    "FleetHealth": "apex_tpu.serving.fleet",
    "FleetFaultPlan": "apex_tpu.serving.resilience",
    "FaultPlan": "apex_tpu.serving.resilience",
    "FaultSpec": "apex_tpu.serving.resilience",
    "ResilienceConfig": "apex_tpu.serving.resilience",
    "HealthMonitor": "apex_tpu.serving.resilience",
    "EngineFault": "apex_tpu.serving.resilience",
    "InjectedFault": "apex_tpu.serving.resilience",
    "EngineFailed": "apex_tpu.serving.resilience",
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(target)
    value = mod if target.endswith("." + name) else getattr(mod, name)
    globals()[name] = value
    return value
