"""Refcounted page allocator for the paged KV cache — host policy.

The paged cache layout (``gpt.decode_step(..., table=...)``) stores a
GLOBAL pool of fixed-size pages ``[num_pages, heads, P, head_dim]``
plus one block table row ``[max_pages] int32`` per slot mapping the
slot's logical horizon chunks onto physical pages. This module owns
the host side of that indirection: which pages are free, which are
pinned by how many slots (copy-on-write prefix sharing refcounts), and
when an admission must be refused for lack of pages (the scheduler's
backpressure signal).

Layout contract (single-sourced here; the engine and tests import the
constants rather than re-deriving them):

- page ``SINK`` (0) is the shared garbage page: never allocated, the
  redirect target of every released slot's table row. Done-but-live
  decode lanes keep writing their frozen column each step
  (``gpt.decode_steps`` freezes ``pos``, not the write), so a released
  slot's row must keep pointing at writable memory — the sink absorbs
  those writes, and nothing ever reads it through an unmasked column.
- allocatable pages are ``1 .. num_pages - 1``; ``capacity`` is their
  count.
- a page with ``refcount > 1`` is SHARED (a registered prefix pinned
  by its registration plus every slot currently mapping it). Shared
  pages are read-only by construction: admission maps them into the
  table row's prefix region and every write a slot issues (tail
  insert, decode column, speculative multi-column) lands at logical
  columns ``>= prefix_len`` — private pages. "First write allocates"
  therefore happens at admission time, where the private tail/decode
  pages are allocated, and a shared page can never be dirtied.

Everything here is O(1)/O(k) numpy-free host arithmetic — the
allocator never touches the device; tables travel to the device as
DATA on each compiled dispatch (never as shapes: the PAGE-TABLE-STATIC
lint rule polices that the table geometry is config-derived).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

#: the reserved garbage/sink page index — never allocated, always the
#: redirect target of freed table rows (see module docstring)
SINK = 0


class PagesExhausted(RuntimeError):
    """Allocation refused: fewer free pages than requested. Carries
    the shortfall so the scheduler's backpressure path can report how
    far over capacity the admission was (and an ingress layer can turn
    it into a 429 with a meaningful hint)."""

    def __init__(self, requested: int, free: int):
        super().__init__(
            f"page pool exhausted: requested {requested} pages, "
            f"{free} free")
        self.requested = requested
        self.free = free


class PageAllocator:
    """Free-list + refcount accounting over ``num_pages`` pages of
    ``page_size`` tokens each (page 0 reserved as the sink).

    >>> alloc = PageAllocator(num_pages=9, page_size=8)
    >>> pages = alloc.alloc(3)          # 3 private pages, refcount 1
    >>> alloc.share(pages[:1])          # pin page (a prefix mapping)
    >>> alloc.free(pages)               # refcounts drop; page 0 of the
    ...                                 # three stays alive (still shared)

    ``used_tokens`` tracks the live-token occupancy the fragmentation
    gauge is computed from: internal fragmentation is the gap between
    the tokens a slot's pages COULD hold and the tokens they DO hold —
    ``1 - used_tokens / (pages_in_use * page_size)``.
    """

    __slots__ = ("num_pages", "page_size", "_free", "_ref",
                 "used_tokens", "allocs_total", "frees_total",
                 "shares_total", "host_pages", "host_bytes",
                 "swap_outs_total", "swap_ins_total")

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages {num_pages} must be >= 2 (page 0 is the "
                f"reserved sink)")
        if page_size < 1:
            raise ValueError(f"page_size {page_size} must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list, ascending pop order for determinism (tests
        # and fault replay see the same page ids for the same sequence
        # of alloc/free calls)
        self._free: List[int] = list(range(num_pages - 1, SINK, -1))
        self._ref = [0] * num_pages
        #: live tokens currently mapped onto allocated pages (the
        #: occupancy numerator; the engine adds/removes per admission/
        #: release)
        self.used_tokens = 0
        self.allocs_total = 0
        self.frees_total = 0
        self.shares_total = 0
        #: host-tier occupancy (the swap tier under this pool — see
        #: serving/hostswap.py): pages currently parked in host RAM
        #: and their byte footprint, plus cumulative swap traffic. The
        #: engine notes swaps here so ``stats()`` is the one snapshot
        #: the gauges and flight recorder read. Survives ``reset()``:
        #: a fault rebuild wipes the DEVICE pool, but parked host
        #: payloads stay valid (they were copied out).
        self.host_pages = 0
        self.host_bytes = 0
        self.swap_outs_total = 0
        self.swap_ins_total = 0

    # -- core ----------------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable pages (``num_pages - 1`` — the sink is not)."""
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.capacity - len(self._free)

    @property
    def shared_pages(self) -> int:
        """Pages pinned by more than one holder (CoW prefix pages with
        at least one live mapping beyond the registration pin)."""
        return sum(1 for r in self._ref if r > 1)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Pop ``n`` pages (refcount 1 each); raises
        :class:`PagesExhausted` without side effects when fewer are
        free — the all-or-nothing contract admission needs."""
        if n > len(self._free):
            raise PagesExhausted(n, len(self._free))
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._ref[p] = 1
        self.allocs_total += n
        return out

    def share(self, pages: Sequence[int]) -> None:
        """Pin already-allocated pages one more time (a slot mapping a
        registered prefix's pages, or a second registration pin)."""
        for p in pages:
            if p == SINK or self._ref[p] < 1:
                raise ValueError(
                    f"share of page {p} which is not allocated")
            self._ref[p] += 1
        self.shares_total += len(pages)

    def free(self, pages: Sequence[int]) -> int:
        """Drop one pin from each page; pages reaching refcount 0
        return to the free list. Returns how many were actually
        released. ``SINK`` entries are ignored (a table row's redirect
        padding)."""
        released = 0
        for p in pages:
            if p == SINK:
                continue
            if self._ref[p] < 1:
                raise ValueError(f"double free of page {p}")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
                released += 1
        self.frees_total += released
        return released

    def reset(self) -> None:
        """Every page free (a fault rebuild — the scheduler replays
        interrupted requests, which re-allocate deterministically)."""
        self._free = list(range(self.num_pages - 1, SINK, -1))
        self._ref = [0] * self.num_pages
        self.used_tokens = 0

    # -- host tier -----------------------------------------------------------

    def note_swap_out(self, n_pages: int, nbytes: int) -> None:
        """Record ``n_pages`` leaving the device pool for host RAM
        (``nbytes`` of storage-form payload). Pure accounting — the
        actual gather/free is the engine's."""
        self.host_pages += n_pages
        self.host_bytes += nbytes
        self.swap_outs_total += n_pages

    def note_swap_in(self, n_pages: int, nbytes: int) -> None:
        """Record ``n_pages`` returning from host RAM to the device
        pool (or being dropped after a recompute-resume — either way
        the host tier no longer holds them)."""
        self.host_pages -= n_pages
        self.host_bytes -= nbytes
        self.swap_ins_total += n_pages

    def note_swap_drop(self, n_pages: int, nbytes: int) -> None:
        """Record a parked payload discarded without a device scatter
        (capacity eviction or a recompute-resume) — it leaves the host
        tier but is not a swap-in."""
        self.host_pages -= n_pages
        self.host_bytes -= nbytes

    # -- observability -------------------------------------------------------

    def fragmentation(self) -> float:
        """Internal fragmentation of the pages in use: ``1 -
        used_tokens / (pages_in_use * page_size)`` — 0.0 when every
        allocated page is full (or none is allocated). The contiguous
        layout's analogue of this number is what the paged cache
        exists to crush: there, every slot strands ``S - len`` tokens."""
        cap = self.pages_in_use * self.page_size
        if cap <= 0:
            return 0.0
        return max(0.0, 1.0 - self.used_tokens / cap)

    def stats(self) -> Dict[str, float]:
        """The page-occupancy snapshot the scheduler gauges/flight-
        records: pool geometry, live usage, sharing, fragmentation."""
        return {
            "pages_total": float(self.capacity),
            "pages_free": float(self.free_pages),
            "pages_in_use": float(self.pages_in_use),
            "pages_shared": float(self.shared_pages),
            "used_tokens": float(self.used_tokens),
            "fragmentation": self.fragmentation(),
            "allocs_total": float(self.allocs_total),
            "frees_total": float(self.frees_total),
            "shares_total": float(self.shares_total),
            "pages_swapped": float(self.host_pages),
            "swap_bytes": float(self.host_bytes),
            "swap_outs_total": float(self.swap_outs_total),
            "swap_ins_total": float(self.swap_ins_total),
        }
