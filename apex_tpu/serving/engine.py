"""Slot-based continuous-batching decode engine — the device loop.

XLA's static-shape world forbids vLLM's dynamic batch: instead a fixed
batch of ``B`` decode *slots* drives ONE compiled per-token program, and
requests flow through slots. All per-request state the device needs —
position, remaining token budget, done flag, eos id, temperature /
top-k / top-p / PRNG key — lives in ``[B]`` device vectors, so the three
compiled programs are trace-stable across the whole serving lifetime:

- ``step``:   one ``gpt.decode_steps`` chunk — ``decode_chunk``
  fused per-token steps (each one ``gpt.decode_step`` over all B slots
  at their own positions + one per-slot
  :func:`apex_tpu.serving.sampling.draw_slots`) in ONE compiled
  ``lax.scan``, emitting ``[B, decode_chunk]`` tokens + finish flags
  per dispatch so the multi-ms tunnel/dispatch cost is paid once per
  chunk instead of once per token,
- ``admit``:  prefill ONE request's prompt at the static padded length
  (``gpt.prefill_at`` — causal attention makes the padded forward exact
  for the real tokens), draw its first token, insert the KV block into
  the shared cache (``gpt.cache_insert_slot``), and scatter the slot's
  state vectors at a traced slot index,
- ``retire``: force a slot done (deadline expiry).

A slot's token stream is bit-identical to a solo ``gpt.generate`` run of
the same request (same key, params) — the continuous-batching oracle
test pins this token-for-token, and ``compiled_cache_sizes`` pins that
no program recompiles after warmup. Host-side policy (queueing,
deadlines, metrics) lives in :mod:`apex_tpu.serving.scheduler`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.models import gpt
from apex_tpu.serving import sampling


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static engine geometry — everything that shapes the compiled
    programs. ``max_prompt_len`` is the single padded prefill length
    (one admission program for every prompt); ``max_seq_len`` is the
    per-slot KV horizon (prompt + generated tokens, ``<= cfg.seq_len``
    for the position table)."""

    slots: int = 4
    max_prompt_len: int = 64
    max_seq_len: int = 128
    pad_token_id: int = 0
    #: tokens decoded per compiled ``step`` dispatch
    #: (``gpt.decode_steps``): raising it amortises the per-dispatch
    #: tunnel latency over n tokens at the cost of admission latency —
    #: queued requests wait for the in-flight chunk, and a slot that
    #: finishes mid-chunk rides out the rest emitting pad. Token
    #: streams are bit-identical at every setting (the chunk-parity
    #: test pins chunk=8 against chunk=1 against solo generate).
    decode_chunk: int = 1


#: eos sentinel in the per-slot eos vector: no stop token for this slot
#: (single-sourced from the decode loop that interprets it)
_NO_EOS = gpt._NO_EOS_SENTINEL


class Engine:
    """Compiled slot engine over ``mesh`` (tp sharding like the rest of
    the decode path; dp/pp axes must be 1 — decode state is replicated).

    The class owns the device buffers (cache + slot-state vectors) and
    exposes host-facing ``admit`` / ``step`` / ``retire``; each call
    fetches only the tiny per-slot outputs.
    """

    def __init__(self, cfg: "gpt.GPTConfig", params, mesh,
                 engine_cfg: Optional[EngineConfig] = None, **overrides):
        ecfg = engine_cfg or EngineConfig(**overrides)
        if engine_cfg is not None and overrides:
            raise ValueError("pass engine_cfg or field overrides, not both")
        if ecfg.slots < 1:
            raise ValueError("need at least one slot")
        if not 1 <= ecfg.max_prompt_len <= ecfg.max_seq_len:
            raise ValueError(
                f"max_prompt_len {ecfg.max_prompt_len} must be in "
                f"[1, max_seq_len={ecfg.max_seq_len}]")
        if ecfg.max_seq_len > cfg.seq_len:
            raise ValueError(
                f"max_seq_len {ecfg.max_seq_len} exceeds the position "
                f"table (cfg.seq_len={cfg.seq_len})")
        if ecfg.decode_chunk < 1:
            raise ValueError(
                f"decode_chunk {ecfg.decode_chunk} must be >= 1")
        gpt._check_stop_tokens(cfg, None, ecfg.pad_token_id)
        for axis in ("dp", "pp", "cp", "ep"):
            if axis in mesh.shape and mesh.shape[axis] != 1:
                raise ValueError(
                    f"serving engine shards over tp only; mesh has "
                    f"{axis}={mesh.shape[axis]}")
        self.cfg = cfg
        self.engine_cfg = ecfg
        self._mesh = mesh
        self._params = params
        self._sentinel = None  # lazily via recompile_sentinel()
        self._build()
        self.cache, self.state = self._init(params)

    # -- compiled programs -------------------------------------------------

    def _build(self):
        cfg, ecfg, mesh = self.cfg, self.engine_cfg, self._mesh
        pspecs = gpt.param_specs(cfg)
        B = ecfg.slots
        pad = jnp.int32(ecfg.pad_token_id)
        # cache [l, 2, B, heads, S, d]: heads are the tp-sharded dim
        cache_spec = P(None, None, None, cfg.axis, None, None)
        state_spec = {k: P() for k in (
            "tok", "pos", "remaining", "done", "temp", "top_k", "top_p",
            "key", "eos")}

        def init_local(params):
            cache = gpt.init_cache(cfg, params, B, max_len=ecfg.max_seq_len)
            state = {
                "tok": jnp.full((B,), pad, jnp.int32),
                "pos": jnp.zeros((B,), jnp.int32),
                "remaining": jnp.zeros((B,), jnp.int32),
                "done": jnp.ones((B,), bool),   # every slot starts free
                "temp": jnp.zeros((B,), jnp.float32),
                "top_k": jnp.zeros((B,), jnp.int32),
                "top_p": jnp.ones((B,), jnp.float32),
                "key": jnp.zeros((B, 2), jnp.uint32),
                "eos": jnp.full((B,), _NO_EOS, jnp.int32),
            }
            return cache, state

        def step_local(params, cache, state):
            # the whole per-token body (decode + per-slot draw +
            # eos/budget masking) lives in gpt.decode_steps — ONE
            # compiled scan of decode_chunk steps per dispatch
            return gpt.decode_steps(
                cfg, params, cache, state, ecfg.decode_chunk,
                pad_token_id=ecfg.pad_token_id)

        def admit_local(params, cache, state, slot, prompt, p_len,
                        max_tokens, temp, top_k, top_p, key, eos):
            block, logits0 = gpt.prefill_at(
                cfg, params, prompt[None], p_len - 1,
                max_len=ecfg.max_prompt_len)
            # the [1]-shaped draw_slots call IS the solo-generate first
            # draw (same [1, vocab] gumbel shape, same fold index)
            one = lambda v, dt: jnp.reshape(v, (1,)).astype(dt)
            first = sampling.draw_slots(
                logits0, key[None], one(p_len - 1, jnp.int32),
                one(temp, jnp.float32), one(top_k, jnp.int32),
                one(top_p, jnp.float32))[0]
            cache = gpt.cache_insert_slot(cache, block, slot)
            hit_eos = (eos >= 0) & (first == eos)
            done0 = hit_eos | (max_tokens <= 1)
            upd = lambda a, v: a.at[slot].set(jnp.asarray(v, a.dtype))
            state = {
                "tok": upd(state["tok"], first),
                "pos": upd(state["pos"], p_len),
                "remaining": upd(state["remaining"], max_tokens - 1),
                "done": upd(state["done"], done0),
                "temp": upd(state["temp"], temp),
                "top_k": upd(state["top_k"], top_k),
                "top_p": upd(state["top_p"], top_p),
                "key": state["key"].at[slot].set(key),
                "eos": upd(state["eos"], eos),
            }
            return cache, state, first, hit_eos, done0

        def retire_local(state, slot):
            return {**state, "done": state["done"].at[slot].set(True)}

        # cache + state are donated: the engine rebinds self.cache /
        # self.state from each call's outputs, and without donation
        # every step/admit copies the whole [l, 2, B, hl, S, d] cache
        # just to update one slot's column (CPU-mesh A/B in
        # docs/DESIGN.md "Serving"; re-measure on chip)
        sm = lambda f, in_specs, out_specs, donate=(): jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False),
            donate_argnums=donate)
        scalar = P()
        self._init = sm(init_local, (pspecs,), (cache_spec, state_spec))
        self._step = sm(
            step_local, (pspecs, cache_spec, state_spec),
            (cache_spec, state_spec, scalar, scalar), donate=(1, 2))
        self._admit = sm(
            admit_local,
            (pspecs, cache_spec, state_spec) + (scalar,) * 9,
            (cache_spec, state_spec, scalar, scalar, scalar),
            donate=(1, 2))
        self._retire = sm(retire_local, (state_spec, scalar), state_spec,
                          donate=(0,))

    # -- host API ----------------------------------------------------------

    @property
    def slots(self) -> int:
        return self.engine_cfg.slots

    def pad_prompt(self, prompt) -> np.ndarray:
        """Right-pad ``prompt`` (1-D ints) to ``max_prompt_len``
        (validating its length) — the static admission shape."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or not 1 <= prompt.size <= \
                self.engine_cfg.max_prompt_len:
            raise ValueError(
                f"prompt must be 1-D with 1..{self.engine_cfg.max_prompt_len}"
                f" tokens, got shape {prompt.shape}")
        out = np.full((self.engine_cfg.max_prompt_len,),
                      self.engine_cfg.pad_token_id, np.int32)
        out[:prompt.size] = prompt
        return out

    def admit(self, slot: int, prompt, max_tokens: int, *,
              temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
              seed: Optional[int] = None,
              eos_token_id: Optional[int] = None) -> Tuple[int, bool, bool]:
        """Admit one request into ``slot``: prefill + first token. Returns
        ``(first_token, hit_eos, finished)`` — ``finished`` True when the
        request is already complete after its first token (eos, or a
        budget of 1). ``max_tokens`` must fit the slot's cache horizon."""
        if not 0 <= slot < self.slots:
            raise ValueError(
                f"slot {slot} outside [0, {self.slots}) — a traced "
                f"out-of-range index would silently clamp into a "
                f"neighbouring slot's cache")
        # same stop-token contract as gpt.generate (rejects vocab-range
        # violations AND an explicit -1, which would alias the
        # no-eos sentinel)
        gpt._check_stop_tokens(self.cfg, eos_token_id, None)
        prompt = np.asarray(prompt, np.int32)
        padded = self.pad_prompt(prompt)
        room = self.engine_cfg.max_seq_len - prompt.size
        if max_tokens < 1 or max_tokens > room:
            raise ValueError(
                f"max_tokens {max_tokens} outside [1, {room}] for a "
                f"{prompt.size}-token prompt at max_seq_len "
                f"{self.engine_cfg.max_seq_len}")
        key = (jax.random.PRNGKey(seed) if seed is not None
               else jnp.zeros((2,), jnp.uint32))
        eos = _NO_EOS if eos_token_id is None else int(eos_token_id)
        self.cache, self.state, first, hit_eos, done = self._admit(
            self._params, self.cache, self.state, np.int32(slot), padded,
            np.int32(prompt.size), np.int32(max_tokens),
            np.float32(temperature), np.int32(top_k), np.float32(top_p),
            jnp.asarray(key, jnp.uint32), np.int32(eos))
        return int(first), bool(hit_eos), bool(done)

    def step(self) -> Tuple[np.ndarray, np.ndarray]:
        """One decode chunk over every slot — ``decode_chunk`` fused
        per-token steps in one dispatch. Returns ``(tokens [B, n],
        finished [B, n])`` with ``n = decode_chunk``; column ``j`` holds
        step ``j``'s emissions, ``pad_token_id`` for slots that were
        done entering that step (a slot that finishes at column ``j``
        emits pad from ``j + 1`` on)."""
        self.cache, self.state, emit, finished = self._step(
            self._params, self.cache, self.state)
        return np.asarray(emit), np.asarray(finished)

    def retire(self, slot: int) -> None:
        """Force ``slot`` done (scheduler deadline expiry). The slot's
        lane keeps riding the compiled step unmodified; its output is
        pad until the next admission overwrites the state."""
        self.state = self._retire(self.state, np.int32(slot))

    def compiled_cache_sizes(self) -> Dict[str, Any]:
        """jit-cache entry count per program — the trace-stability
        probe: after warmup each must stay at 1 no matter how many
        requests were admitted (the oracle test asserts this)."""
        out = {}
        for name in ("init", "step", "admit", "retire"):
            fn = getattr(self, f"_{name}")
            size = getattr(fn, "_cache_size", None)
            out[name] = size() if callable(size) else None
        return out

    # -- recompile sentinel (apex_tpu.telemetry.recompile) -----------------

    def recompile_sentinel(self, registry=None):
        """The engine's installed
        :class:`apex_tpu.telemetry.recompile.RecompileSentinel`, created
        on first call with all four compiled programs tracked (so
        ``compiles_total()["tracked"]`` attributes growth to
        init/step/admit/retire by name). Pass ``registry`` on the first
        call to mirror compile/alarm counters into ``/metrics`` —
        passing it once a registry-less sentinel exists raises rather
        than silently dropping the wiring (the counters would simply
        never appear in scrapes)."""
        if self._sentinel is not None and registry is not None \
                and registry is not self._sentinel.registry:
            raise ValueError(
                "this engine's recompile sentinel already exists (an "
                "earlier recompile_sentinel()/recompile_guard() call) "
                "and cannot adopt a different registry retroactively; "
                "pass registry on the FIRST call, or engine.close() to "
                "discard the old sentinel")
        if self._sentinel is None:
            from apex_tpu.telemetry.recompile import RecompileSentinel

            sentinel = RecompileSentinel(registry=registry).install()
            for name in ("init", "step", "admit", "retire"):
                sentinel.track(name, getattr(self, f"_{name}"))
            self._sentinel = sentinel
        return self._sentinel

    def recompile_guard(self, *, raise_on_recompile: bool = True,
                        registry=None):
        """Arm the never-recompile-after-warmup invariant: enter the
        returned context once every program has compiled (one admit +
        one step + one retire cover it) and any later compilation —
        process-wide event or growth of this engine's program caches —
        increments the alarm counter and (by default) raises
        :class:`~apex_tpu.telemetry.recompile.RecompileError`::

            engine/scheduler warmup ...
            with engine.recompile_guard():
                serve_forever()
        """
        return self.recompile_sentinel(registry=registry).guard(
            raise_on_recompile=raise_on_recompile)

    def close(self) -> None:
        """Release process-wide telemetry hooks — the recompile
        sentinel's ``jax.monitoring`` listener stays registered for
        process lifetime otherwise, so engines created in a loop (the
        bench's chunk sweep, a service rebuilding on config reload)
        must close the old one. Idempotent; the engine itself remains
        usable, and a later :meth:`recompile_sentinel` call reinstalls
        a fresh sentinel."""
        if self._sentinel is not None:
            self._sentinel.uninstall()
            self._sentinel = None
