"""Slot-based continuous-batching decode engine — the device loop.

XLA's static-shape world forbids vLLM's dynamic batch: instead a fixed
batch of ``B`` decode *slots* drives ONE compiled per-token program, and
requests flow through slots. All per-request state the device needs —
position, remaining token budget, done flag, eos id, temperature /
top-k / top-p / PRNG key — lives in ``[B]`` device vectors, so the
compiled programs are trace-stable across the whole serving lifetime:

- ``step``:   one ``gpt.decode_steps`` chunk — ``decode_chunk``
  fused per-token steps (each one ``gpt.decode_step`` over all B slots
  at their own positions + one per-slot
  :func:`apex_tpu.serving.sampling.draw_slots`) in ONE compiled
  ``lax.scan``, emitting ``[B, decode_chunk]`` tokens + logprobs +
  finish flags per dispatch so the multi-ms tunnel/dispatch cost is
  paid once per chunk instead of once per token. Per-slot vocab masks
  (constrained decoding) ride every dispatch as one static bool
  argument — all-True rows are bit-identical to no mask. :meth:`Engine.step_async` exposes
  the dispatch as an in-flight :class:`StepHandle` so a pipelined
  scheduler can enqueue the NEXT chunk before fetching this one's
  tokens — serial ``device + host`` becomes ``max(device, host)``.
- ``admit``:  one program per static ``(bucket, k)`` pair — prefill a
  ``[k, bucket]`` batch of right-padded prompts in ONE forward
  (``gpt.prefill_many`` — causal attention makes the padded forward
  exact for every row's real tokens), draw k first tokens, insert k
  KV blocks into the shared cache (``gpt.cache_insert_slots``), and
  scatter k state rows at traced slot indices. The admission ladder
  (``admit_batch_sizes``, e.g. 1/2/4) lets a burst of queued requests
  drain in ~1 dispatch instead of k; the prompt-length ladder
  (``prompt_buckets``, powers of two up to ``max_prompt_len``) lets a
  short prompt pay a short padded forward instead of the full one.
- ``retire``: force a slot done (deadline expiry).

A slot's token stream is bit-identical to a solo ``gpt.generate`` run of
the same request (same key, params) — the continuous-batching oracle
test pins this token-for-token, batched admission is pinned equal to k
single admits, bucketed prefill equal to max-length prefill — and
``compiled_cache_sizes`` pins that no program recompiles after
:meth:`Engine.warmup` (which compiles every (bucket, k) variant up
front). Host-side policy (queueing, deadlines, metrics) lives in
:mod:`apex_tpu.serving.scheduler`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.models import gpt
from apex_tpu.serving import hostswap, sampling
from apex_tpu.serving.pages import SINK, PageAllocator, PagesExhausted
from apex_tpu.telemetry.recompile import expected_compiles
from apex_tpu.serving.resilience import (
    KIND_ERROR,
    KIND_HANG,
    KIND_NAN,
    EngineFault,
    FaultPlan,
    FaultSpec,
    InjectedFault,
)


def default_prompt_buckets(max_prompt_len: int) -> Tuple[int, ...]:
    """The static padded-prefill length ladder: powers of two from 8 up
    to (and always including) ``max_prompt_len``. The floor of 8 keeps
    the compiled-program count small — below it the padded forward is
    already tiny and another bucket would buy nothing but a compile."""
    out: List[int] = []
    v = 8
    while v < max_prompt_len:
        out.append(v)
        v *= 2
    out.append(max_prompt_len)
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static engine geometry — everything that shapes the compiled
    programs. ``max_prompt_len`` caps prompt length (admission pads to
    the smallest bucket that fits, see ``prompt_buckets``);
    ``max_seq_len`` is the per-slot KV horizon (prompt + generated
    tokens, ``<= cfg.seq_len`` for the position table)."""

    slots: int = 4
    max_prompt_len: int = 64
    max_seq_len: int = 128
    pad_token_id: int = 0
    #: tokens decoded per compiled ``step`` dispatch
    #: (``gpt.decode_steps``): raising it amortises the per-dispatch
    #: tunnel latency over n tokens at the cost of admission latency —
    #: queued requests wait for the in-flight chunk, and a slot that
    #: finishes mid-chunk rides out the rest emitting pad. Token
    #: streams are bit-identical at every setting (the chunk-parity
    #: test pins chunk=8 against chunk=1 against solo generate).
    decode_chunk: int = 1
    #: static ladder of padded prefill lengths; admission picks the
    #: smallest bucket >= the (batch-max) prompt length, so a 4-token
    #: prompt pays an 8-wide padded forward instead of the full
    #: ``max_prompt_len`` one. None = :func:`default_prompt_buckets`
    #: (powers of two up to ``max_prompt_len``). Must be strictly
    #: increasing and end at ``max_prompt_len``. Each (bucket, k) pair
    #: is one compiled admission program — ``Engine.warmup()`` compiles
    #: them all so steady state never traces.
    prompt_buckets: Optional[Tuple[int, ...]] = None
    #: static ladder of admission batch sizes k: ``admit_many`` splits
    #: a burst of queued requests into ladder-sized groups (largest
    #: first), each group ONE prefill forward + ONE dispatch. None =
    #: (1, 2, 4) capped at ``slots``. Must be strictly increasing and
    #: start at 1 (any group count decomposes).
    admit_batch_sizes: Optional[Tuple[int, ...]] = None
    #: speculative decoding: draft tokens per wave (0 disables — no
    #: spec step program, no history buffer; the historical engine).
    #: With ``spec_k > 0`` the engine compiles a SECOND step variant
    #: (``gpt.decode_steps_spec``): each of the chunk's
    #: ``decode_chunk`` scan iterations drafts ``spec_k`` candidates
    #: from the slot's token history (device-side n-gram suffix match
    #: — no second model), verifies all ``spec_k + 1`` positions in
    #: ONE batched target forward, and accept-prefix-selects — a chunk
    #: emits up to ``decode_chunk * (spec_k + 1)`` tokens per slot for
    #: roughly one plain chunk's weight traffic when drafts hit.
    #: Emitted streams are BIT-IDENTICAL to the plain path (greedy and
    #: sampled — verification is token-matching against the target's
    #: own draws at the same key fold points), so the scheduler's
    #: payoff gate flips between the two pre-warmed variants freely.
    spec_k: int = 0
    #: token-history ring width per slot — the n-gram drafter's match
    #: window (newest-last, -1 sentinel padding; seeded from the
    #: prompt tail at admission). Only meaningful with ``spec_k > 0``.
    spec_hist: int = 32
    #: shared-prefix pool pages (0 disables — no extra compiled
    #: programs, no pool buffer). A common prompt prefix
    #: (:meth:`Engine.register_prefix` — a system-prompt template) is
    #: prefilled ONCE into a pool page; a request whose prompt starts
    #: with it (:meth:`Engine.match_prefix`, hash-keyed at
    #: bucket-aligned split points) admits by COPYING the pooled K/V
    #: into its slot via a compiled gather and prefilling only the
    #: tail — admission cost drops from the full prompt bucket to the
    #: tail bucket. One compiled program per (prefix bucket, tail
    #: bucket) pair plus one pool-insert per prefix bucket, all
    #: compiled by :meth:`Engine.warmup`.
    prefix_pool_slots: int = 0
    #: paged KV cache: > 0 switches the slot cache from one contiguous
    #: ``[max_seq_len]``-horizon stripe per slot to a GLOBAL pool of
    #: ``page_size``-token pages plus one ``[max_pages] int32`` block
    #: table row per slot (``max_pages = ceil(max_seq_len /
    #: page_size)`` — config-derived, never request-derived: tables
    #: are DATA in the compiled programs, so one program serves every
    #: table content). A 12-token request then pins
    #: ``ceil((12 + max_tokens) / page_size)`` pages instead of a full
    #: ``max_seq_len`` stripe — the fragmentation-free capacity play —
    #: and prefix-pool hits share the prefix's pages copy-on-write
    #: (refcounted; the prefix region is read-only by construction, so
    #: the "first write" that would allocate is the admission's own
    #: private tail/decode pages). 0 = the historical contiguous
    #: layout. Emitted streams are bit-identical either way (the paged
    #: == contiguous oracle pins it).
    page_size: int = 0
    #: pages in the global pool (paged mode only). 0 = auto-size to
    #: ``slots * max_pages + 1`` — every slot can hold a worst-case
    #: request, plus the reserved sink page 0 (freed slots' table rows
    #: redirect there so their frozen decode lanes write garbage into
    #: garbage). Set lower to oversubscribe HBM against a mixed-length
    #: workload; admission then backpressures through
    #: :class:`~apex_tpu.serving.pages.PagesExhausted` when the pool
    #: runs dry (the scheduler keeps the queue and sheds per policy).
    num_pages: int = 0
    #: chunked prefill: > 0 admits prompts LONGER than this in
    #: ``prefill_chunk``-token slices — chunk 0 through a bucket-sized
    #: cold prefill, later chunks through ``gpt.prefill_extend`` over
    #: the already-ingested prefix — with the scheduler free to run
    #: decode waves between chunk dispatches, so a long-prompt
    #: admission no longer stalls every other stream's TTFT for one
    #: monolithic forward. Must be a prompt bucket dividing
    #: ``max_prompt_len``. One compiled extend variant per chunk index
    #: (``max_prompt_len / prefill_chunk - 1`` of them), all warmed.
    #: Streams are bit-identical to a monolithic admission whenever
    #: cold prefill runs the materialised-scores attention (every
    #: off-TPU config — the ``gpt.prefill_extend`` parity contract).
    #: 0 disables.
    prefill_chunk: int = 0
    #: static ladder of decode-chunk STEP VARIANTS: each value is one
    #: compiled step program (spec variants cross with ``spec_ks``),
    #: all compiled by :meth:`Engine.warmup` and tracked per variant,
    #: so a self-tuning scheduler (``serving.tuner``) switches chunk
    #: size per dispatch with the recompile guard armed. Must be
    #: strictly increasing and contain ``decode_chunk`` (the base
    #: operating point). None = ``(decode_chunk,)`` — the historical
    #: single-variant engine. Token streams are bit-identical at every
    #: rung (the chunk-parity oracle).
    decode_chunks: Optional[Tuple[int, ...]] = None
    #: static ladder of speculative draft widths: each non-zero value
    #: is one compiled spec step variant PER decode-chunk rung (the
    #: tuner's ``spec_k=0`` rung is the plain variant, not a program).
    #: Must be strictly increasing, all >= 1, and contain ``spec_k``
    #: when ``spec_k > 0``. None = ``(spec_k,)`` if ``spec_k > 0``
    #: else no speculation. ``spec_ks`` with ``spec_k == 0`` is valid:
    #: the engine carries the drafter machinery and warm spec variants
    #: but dispatches plain until a tuner asks otherwise.
    spec_ks: Optional[Tuple[int, ...]] = None
    #: batched multi-LoRA adapter pool rows (0 disables — no pool
    #: buffer, no extra program arguments; the historical engine).
    #: With ``adapter_slots > 0`` every dense seam of every forward
    #: (prefill / extend / decode / verify) gains a per-slot low-rank
    #: delta gathered from a static ``[n_adapters, r, ...]`` pool by a
    #: ``[B] int32`` adapter-id table — ids are DATA (the vocab-mask /
    #: block-table pattern), so ONE compiled program serves every
    #: tenant mix and the recompile guard stays flat across adapter
    #: registration and admission churn. Row 0 is the PINNED all-zero
    #: adapter: base traffic decodes numerically exact (the delta is
    #: an exact zero), tenants register into rows 1..n-1 via
    #: :meth:`Engine.register_adapter` (after :meth:`Engine.warmup`,
    #: the prefix-pool lifecycle). The pool is never donated, so it
    #: survives :meth:`Engine.rebuild_slots` and fault replay serves
    #: the same weights.
    adapter_slots: int = 0
    #: low-rank adapter rank r — compile-time static (ADAPTER-STATIC:
    #: every registered adapter shares it; a per-tenant rank would be
    #: a shape ladder and recompile per tenant).
    adapter_rank: int = 8
    #: LoRA scaling numerator: deltas apply as ``(alpha / r) * B A x``.
    adapter_alpha: float = 16.0
    #: host-RAM page tier under the device pool (paged mode only —
    #: requires ``page_size > 0``). True compiles the swap programs
    #: (``pages_out``/``pages_in`` gather/scatter over the page dim,
    #: one variant per power-of-two swap-batch rung, all warmed) and
    #: arms :meth:`Engine.park_slot` / :meth:`Engine.resume_slot`: a
    #: paused conversation's private pages move to host buffers in
    #: storage form (bit-exact round trip, quantized planes included)
    #: so active streams keep every HBM page, and the scheduler can
    #: oversubscribe the pool far past ``num_pages``. Also lifts the
    #: ``register_adapter`` hard cap: cold adapter rows spill to host
    #: under the same LRU and page back in on demand (ids stay DATA —
    #: no recompile). False = the historical hard-capped engine.
    host_swap: bool = False
    #: host-tier capacity in PAGES (0 = unbounded): parking past it
    #: LRU-drops the coldest payloads, whose conversations fall back
    #: to recompute-resume from the emitted-prefix snapshot.
    host_swap_pages: int = 0
    #: how a parked conversation comes back: ``"swap"`` scatters the
    #: host payload into freshly allocated pages and restores the
    #: slot's state row (PRNG key included — bit-identical
    #: continuation); ``"recompute"`` drops the payload and replays
    #: prompt + emitted prefix through the fault-replay machinery
    #: (also bit-identical — same seed, suppressed re-emission);
    #: ``"auto"`` prices the two per resume from measured swap-in cost
    #: vs replay cost and picks the cheaper. Both paths are pinned
    #: equal, so the policy is pure performance.
    resume_policy: str = "auto"


#: eos sentinel in the per-slot eos vector: no stop token for this slot
#: (single-sourced from the decode loop that interprets it)
_NO_EOS = gpt._NO_EOS_SENTINEL


@dataclasses.dataclass(frozen=True)
class Admission:
    """One admission request — the argument row of
    :meth:`Engine.admit_many` (``Engine.admit``'s keyword surface as
    data, so a batch of them can ride one dispatch).

    ``allowed_tokens`` (optional) is the constrained-decoding vocab
    whitelist for the FIRST token — the schema DFA's initial allowed
    set; it also seeds the slot's per-step mask
    (:meth:`Engine.set_slot_mask` advances it between chunks). ``None``
    = unconstrained (and resets any stale mask the slot carried).

    ``adapter`` selects the request's LoRA adapter row (0 = the pinned
    base adapter; rows >= 1 come from
    :meth:`Engine.register_adapter`). It rides the admission prefill
    AND the slot's decode id-table entry, so every token of the
    request — prefill, decode, speculative verify — sees the same
    weights.

    ``prefix_page``/``prefix_len`` (optional) ride a prefix-pool hit
    (:meth:`Engine.match_prefix`): ``prompt`` is still the FULL token
    sequence, but its first ``prefix_len`` tokens (which must equal the
    registered prefix — validated) are copied from pool page
    ``prefix_page`` instead of prefilled, and only the tail runs a
    forward. Streams are bit-identical to a cold admission of the same
    prompt whenever cold prefill runs the materialised-scores
    attention (every off-TPU config; flash prefill differs at the
    reduction-order ulp level — see ``gpt.prefill_extend``)."""

    slot: int
    prompt: Any
    max_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    eos_token_id: Optional[int] = None
    allowed_tokens: Optional[Sequence[int]] = None
    prefix_page: Optional[int] = None
    prefix_len: int = 0
    adapter: int = 0


@dataclasses.dataclass(frozen=True)
class AdmitResult:
    """Per-request outcome of :meth:`Engine.admit_many`. ``finished``
    is True when the request is already complete after its first token
    (eos, or a budget of 1). ``logprob`` is the model's log-probability
    of the first token (log-softmax of the raw prefill logits).
    ``bucket``/``batch_size``/``group`` record which compiled admission
    variant served it and which dispatch group of the call it rode —
    the scheduler's admission telemetry."""

    first_token: int
    hit_eos: bool
    finished: bool
    bucket: int
    batch_size: int
    group: int
    logprob: float = 0.0


def _pad_span(block, span: int):
    """Zero-pad a cache block pytree (``[l, 2, k, hl, T(, d)]`` leaves)
    to ``span`` columns on the horizon dim (4) — the paged insert's
    page-alignment shim: ``gpt.cache_insert_pages`` writes whole pages,
    and the pad columns land either in the slot's own not-yet-decoded
    cells or in the sink page (masked garbage both ways)."""
    def f(x):
        pad = span - x.shape[4]
        if pad <= 0:
            return x
        w = [(0, 0)] * x.ndim
        w[4] = (0, pad)
        return jnp.pad(x, w)

    return jax.tree.map(f, block)


class ChunkedAdmission:
    """Host progress of one chunked-prefill admission
    (``EngineConfig.prefill_chunk``): created by
    :meth:`Engine.admit_chunked_start` (which dispatches chunk 0),
    advanced one chunk-forward per :meth:`Engine.admit_chunked_step`
    call — the scheduler interleaves decode waves between calls — and
    finished by the same method returning the :class:`AdmitResult`.
    ``chunks_total`` counts the prefill forwards (the admission's
    device dispatches are ``chunks_total + 1`` including the finish)."""

    __slots__ = ("admission", "prompt", "p_len", "chunks_total",
                 "next_chunk", "slot", "_logits")

    def __init__(self, admission: Admission, prompt: np.ndarray,
                 p_len: int, chunks_total: int):
        self.admission = admission
        self.prompt = prompt
        self.p_len = p_len
        self.chunks_total = chunks_total
        self.next_chunk = 1          # chunk 0 dispatched at start
        self.slot = admission.slot
        self._logits = None          # the final chunk's device logits

    @property
    def done_prefilling(self) -> bool:
        """True once every prefill chunk is dispatched (the next
        :meth:`Engine.admit_chunked_step` call runs the finish)."""
        return self.next_chunk >= self.chunks_total


def _threefry_key_data(seed: int) -> np.ndarray:
    """``jax.random.PRNGKey(seed)``'s raw data, computed host-side with
    numpy for the common domain (non-negative int32 seeds — the
    threefry key is just the packed seed, zero hi word, no hashing;
    pinned bit-identical against the real PRNGKey in the tests).
    Avoids dispatching + FETCHING one tiny device program per seeded
    request on the admission hot path — through the chip tunnel each
    fetch is a multi-ms round trip, which would cancel the k→1
    dispatch amortization batched admission exists for. Seeds outside
    that domain (negative, or > 31 bits — whose truncation depends on
    the runtime's x64 mode) take the real PRNGKey, paying the round
    trip to stay bit-stable."""
    if 0 <= seed < 2**31:
        return np.asarray([0, seed], np.uint32)
    return np.asarray(jax.random.PRNGKey(seed), np.uint32)


class StepHandle:
    """One in-flight decode chunk: the ``[B, n]`` token/logprob/
    finished device futures a :meth:`Engine.step_async` dispatch
    returned. ``fetch()`` is the value-fetch sync (per the perf-claims
    convention — ``block_until_ready`` can return at dispatch time
    through the tunnel, a value fetch cannot); it caches, so fetching
    twice costs one transfer.

    Fault injection (:mod:`apex_tpu.serving.resilience`): a plan's
    ``fetch`` seam is consumed on the FIRST fetch only, and a
    ``dispatch``-seam hang spec rides the handle to be applied where a
    hung dispatch is observed — at the fetch."""

    __slots__ = ("_emit", "_logprobs", "_finished", "_out", "_plan",
                 "_hang", "_on_poison", "_valid_dev", "valid", "spec_k",
                 "ncols")

    def __init__(self, emit, logprobs, finished, *,
                 plan: Optional[FaultPlan] = None,
                 hang: Optional[FaultSpec] = None,
                 on_poison: Optional[Any] = None,
                 valid=None, spec_k: int = 0, ncols: int = 0):
        self._emit = emit
        self._logprobs = logprobs
        self._finished = finished
        self._out: Optional[
            Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        self._plan = plan
        self._hang = hang
        self._on_poison = on_poison
        #: speculative chunks only: the ``[B, ncols]`` bool plane
        #: marking which columns carry REAL emissions (rejected draft
        #: lanes and done slots emit pad under False). None for plain
        #: chunks (where every live slot's column is real) — and until
        #: :meth:`fetch` lands the device future.
        self._valid_dev = valid
        self.valid: Optional[np.ndarray] = None
        #: draft tokens per wave of the chunk this handle carries (0 =
        #: plain chunk)
        self.spec_k = spec_k
        #: token columns this chunk emits per slot — ``decode_chunk``
        #: for plain chunks, ``decode_chunk * (spec_k + 1)`` for
        #: speculative ones (the scheduler's in-flight budget guard
        #: prices chunks by this)
        self.ncols = ncols

    @property
    def spec(self) -> bool:
        """True when this handle carries a speculative chunk."""
        return self.spec_k > 0

    def fetch(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Block until the chunk lands; returns ``(tokens [B, n],
        logprobs [B, n], finished [B, n])`` as host arrays."""
        if self._out is not None:
            return self._out
        spec = self._plan.take("fetch") if self._plan is not None else None
        for s in (self._hang, spec):
            if s is not None and s.kind == KIND_HANG:
                self._plan.hang_fn(s.hang_s)
        if spec is not None and spec.kind == KIND_ERROR:
            if self._on_poison is not None:
                self._on_poison()
            raise InjectedFault(
                f"injected device error at fetch: {spec.describe()}",
                point="fetch", spec=spec)
        tokens = np.asarray(self._emit)
        logprobs = np.asarray(self._logprobs)
        finished = np.asarray(self._finished)
        if self._valid_dev is not None:
            self.valid = np.asarray(self._valid_dev)
        if spec is not None and spec.kind == KIND_NAN:
            # what a NaN logit batch looks like by the time the host
            # sees it: garbage token ids in the poisoned lanes
            tokens = tokens.copy()
            rows = [s for s in spec.slots if 0 <= s < tokens.shape[0]]
            tokens[rows, :] = spec.token
        self._out = (tokens, logprobs, finished)
        return self._out


class Engine:
    """Compiled slot engine over ``mesh`` (tp sharding like the rest of
    the decode path; dp/pp axes must be 1 — decode state is replicated).

    The class owns the device buffers (cache + slot-state vectors) and
    exposes host-facing ``admit`` / ``admit_many`` / ``step`` /
    ``step_async`` / ``retire``; each call fetches only the tiny
    per-slot outputs (``step_async`` defers even that).
    """

    def __init__(self, cfg: "gpt.GPTConfig", params, mesh,
                 engine_cfg: Optional[EngineConfig] = None,
                 fault_plan: Optional[FaultPlan] = None, **overrides):
        ecfg = engine_cfg or EngineConfig(**overrides)
        if engine_cfg is not None and overrides:
            raise ValueError("pass engine_cfg or field overrides, not both")
        if ecfg.slots < 1:
            raise ValueError("need at least one slot")
        if not 1 <= ecfg.max_prompt_len <= ecfg.max_seq_len:
            raise ValueError(
                f"max_prompt_len {ecfg.max_prompt_len} must be in "
                f"[1, max_seq_len={ecfg.max_seq_len}]")
        if ecfg.max_seq_len > cfg.seq_len:
            raise ValueError(
                f"max_seq_len {ecfg.max_seq_len} exceeds the position "
                f"table (cfg.seq_len={cfg.seq_len})")
        if ecfg.decode_chunk < 1:
            raise ValueError(
                f"decode_chunk {ecfg.decode_chunk} must be >= 1")
        if ecfg.spec_k < 0:
            raise ValueError(f"spec_k {ecfg.spec_k} must be >= 0")
        self._chunk_ladder = self._resolve_chunk_ladder(ecfg)
        self._spec_ladder = self._resolve_spec_ladder(ecfg)
        if self._spec_ladder and ecfg.spec_hist < 2:
            raise ValueError(
                f"spec_hist {ecfg.spec_hist} must be >= 2 with "
                f"speculation (the drafter matches a 2-token suffix)")
        if self._spec_ladder and cfg.num_experts:
            raise ValueError(
                "speculation does not compose with num_experts > 0: the "
                "batched verify forward routes a different token count "
                "than sequential steps, so MoE expert capacity breaks "
                "spec == plain bit-parity (see gpt.decode_verify)")
        gpt._check_stop_tokens(cfg, None, ecfg.pad_token_id)
        for axis in ("dp", "pp", "cp", "ep"):
            if axis in mesh.shape and mesh.shape[axis] != 1:
                raise ValueError(
                    f"serving engine shards over tp only; mesh has "
                    f"{axis}={mesh.shape[axis]}")
        # -- batched multi-LoRA geometry (all compile-time static:
        # pool rows and rank shape the programs, ids are data —
        # ADAPTER-STATIC) ------------------------------------------------
        if ecfg.adapter_slots < 0:
            raise ValueError(
                f"adapter_slots {ecfg.adapter_slots} must be >= 0")
        self._lora = ecfg.adapter_slots > 0
        if self._lora:
            if ecfg.adapter_rank < 1:
                raise ValueError(
                    f"adapter_rank {ecfg.adapter_rank} must be >= 1")
            if cfg.num_experts:
                raise ValueError(
                    "adapter_slots > 0 does not compose with "
                    "num_experts > 0 (the expert FFN has no per-row "
                    "dense seam to delta — see gpt.init_lora_pool)")
        self._lora_scale = (ecfg.adapter_alpha / ecfg.adapter_rank
                            if self._lora else 0.0)
        self._buckets = self._resolve_buckets(ecfg)
        self._batch_sizes = self._resolve_batch_sizes(ecfg)
        if ecfg.prefix_pool_slots > 0 and cfg.num_experts:
            raise ValueError(
                "prefix_pool_slots > 0 does not compose with "
                "num_experts > 0: MoE expert capacity depends on the "
                "routed token count, so a tail-only extend forward "
                "drops different tokens than the cold full-prompt "
                "prefill and prefix-hit streams would silently "
                "diverge (see gpt.prefill_extend)")
        self._prefix_splits, self._extend_variants = \
            self._resolve_prefix_variants(ecfg, self._buckets)
        # -- paged KV cache geometry (all config-derived constants:
        # tables are data, never shapes — PAGE-TABLE-STATIC) ------------
        if ecfg.page_size < 0 or ecfg.num_pages < 0:
            raise ValueError(
                f"page_size {ecfg.page_size} / num_pages "
                f"{ecfg.num_pages} must be >= 0")
        self._paged = ecfg.page_size > 0
        if not self._paged and ecfg.num_pages:
            raise ValueError(
                "num_pages without page_size — the pool geometry only "
                "exists in paged mode")
        self._max_pages = 0
        self._num_pages = 0
        if self._paged:
            self._max_pages = -(-ecfg.max_seq_len // ecfg.page_size)
            self._num_pages = (ecfg.num_pages
                               or ecfg.slots * self._max_pages + 1)
            if self._num_pages < self._max_pages + 1:
                raise ValueError(
                    f"num_pages {self._num_pages} cannot hold one "
                    f"worst-case request ({self._max_pages} pages) "
                    f"plus the sink page")
            if self._prefix_splits:
                # copy-on-write sharing maps whole pages: only
                # page-aligned split points can share (the tail insert
                # starts at the split, and a mid-page split would make
                # a shared page writable)
                splits = tuple(s for s in self._prefix_splits
                               if s % ecfg.page_size == 0)
                if not splits:
                    raise ValueError(
                        f"prefix_pool_slots={ecfg.prefix_pool_slots} "
                        f"with page_size={ecfg.page_size}: no split "
                        f"point in {self._prefix_splits} is "
                        f"page-aligned — pick a page_size dividing a "
                        f"prompt bucket")
                self._extend_variants = tuple(
                    (ps, tb) for ps, tb in self._extend_variants
                    if ps in splits)
                self._prefix_splits = splits
        # -- host-swap tier geometry (rungs config-derived from the
        # worst-case private page count — HOST-TIER-STATIC) -------------
        if ecfg.resume_policy not in ("auto", "swap", "recompute"):
            raise ValueError(
                f"resume_policy {ecfg.resume_policy!r} must be one of "
                f"'auto' | 'swap' | 'recompute'")
        if ecfg.host_swap_pages < 0:
            raise ValueError(
                f"host_swap_pages {ecfg.host_swap_pages} must be >= 0")
        self._host_swap = bool(ecfg.host_swap)
        if self._host_swap and not self._paged:
            raise ValueError(
                "host_swap requires the paged KV cache (page_size > 0) "
                "— the swap tier moves pages, not contiguous stripes")
        if ecfg.host_swap_pages and not self._host_swap:
            raise ValueError(
                "host_swap_pages without host_swap — the host tier "
                "only exists with host_swap=True")
        self._swap_rungs: Tuple[int, ...] = ()
        if self._host_swap:
            self._swap_rungs = hostswap.swap_rungs(self._max_pages)
        # -- chunked prefill geometry -----------------------------------
        if ecfg.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk {ecfg.prefill_chunk} must be >= 0")
        self._chunk_size = ecfg.prefill_chunk
        if self._chunk_size:
            if cfg.num_experts:
                raise ValueError(
                    "prefill_chunk > 0 does not compose with "
                    "num_experts > 0 (chunked admission rides "
                    "gpt.prefill_extend, which MoE expert capacity "
                    "breaks — see its docstring)")
            if self._chunk_size not in self._buckets:
                raise ValueError(
                    f"prefill_chunk {self._chunk_size} must be one of "
                    f"the prompt buckets {self._buckets} (chunk 0 is a "
                    f"bucket-sized cold prefill)")
            if self._chunk_size >= ecfg.max_prompt_len \
                    or ecfg.max_prompt_len % self._chunk_size:
                raise ValueError(
                    f"prefill_chunk {self._chunk_size} must divide and "
                    f"be smaller than max_prompt_len "
                    f"{ecfg.max_prompt_len} (the chunk ladder is "
                    f"static)")
        self.cfg = cfg
        self.engine_cfg = ecfg
        self._mesh = mesh
        self._params = params
        self._sentinel = None  # lazily via recompile_sentinel()
        #: monotonic admission counter — folded into the default PRNG
        #: key of unseeded requests so concurrent sampled requests never
        #: share a stream (they all drew from the zero key before)
        self._req_counter = 0
        self._warmed = False
        #: chaos harness (resilience.FaultPlan): consulted at the
        #: admit/dispatch/fetch seams; None in production
        self.fault_plan = fault_plan
        self._warming = False   # warmup must never consume plan faults
        #: True after a fault invalidated the donated cache/state —
        #: every device call refuses until rebuild_slots()
        self._poisoned = False
        #: per-slot constrained-decoding vocab masks, host mirror —
        #: all-True rows (the unconstrained default) are bit-identical
        #: to no mask in the draw. The device copy is cached and only
        #: re-uploaded when a row changes (set_slot_mask / admission),
        #: so the steady unconstrained path pays one stale-pointer
        #: check per dispatch, not a [B, vocab] transfer.
        self._masks = np.ones((ecfg.slots, cfg.vocab_size), bool)
        self._masks_dev: Optional[Any] = None
        #: prefix-pool host registry: bucket-aligned key (exact token
        #: tuple) → (page, split); pages hold the registered tokens for
        #: admission-time validation. Device pool built in _build.
        self._prefix_index: Dict[Tuple[int, ...], Tuple[int, int]] = {}
        self._prefix_tokens: Dict[int, Tuple[int, ...]] = {}
        self._prefix_used = 0
        self.pool: Optional[Any] = None
        #: paged-mode host state: the page allocator, the [B, max_pages]
        #: block-table host mirror (device copy cached like the masks —
        #: re-uploaded only when a row changes), per-slot page
        #: bookkeeping, and the registered prefixes' pinned cache pages
        self._page_alloc: Optional[PageAllocator] = None
        self._tables: Optional[np.ndarray] = None
        self._tables_dev: Optional[Any] = None
        self._slot_pages: Dict[int, Tuple[List[int], List[int], int]] = {}
        self._prefix_pages: Dict[int, List[int]] = {}
        if self._paged:
            self._page_alloc = PageAllocator(self._num_pages,
                                             ecfg.page_size)
            self._tables = np.full((ecfg.slots, self._max_pages), SINK,
                                   np.int32)
        #: the single in-progress chunked-prefill admission (None
        #: between chunked admissions; the engine serializes them — the
        #: scratch buffer holds one prompt)
        self._chunked: Optional[ChunkedAdmission] = None
        #: multi-LoRA host state: the per-slot adapter-id table mirror
        #: (device copy cached like the masks — re-uploaded only when
        #: a row changes) and the adapter registry (name → row,
        #: row → metadata incl. the registration seed the post-mortem
        #: replay rebuilds adapters from)
        self._adapter_ids = np.zeros((ecfg.slots,), np.int32)
        self._aids_dev: Optional[Any] = None
        self._adapter_names: Dict[str, int] = {}
        self._adapter_meta: Dict[int, Dict[str, Any]] = {}
        self._adapter_used = 1 if self._lora else 0  # row 0 pinned
        self.adapters: Optional[Any] = None
        #: host-swap tier state: the parked-conversation LRU store
        #: (opaque payloads: storage-form page blocks + the slot's
        #: state row + table/mask/adapter mirrors) and the measured
        #: per-page swap-in cost the auto resume policy prices from
        self._host_tier: Optional[hostswap.HostPageTier] = None
        self._swap_in_ewma_s = 0.0
        if self._host_swap:
            self._host_tier = hostswap.HostPageTier(ecfg.host_swap_pages)
        #: adapter paging (host_swap engines): every registration's
        #: host-side weight rows (virtual id → numpy pytree), the
        #: virtual → physical residency maps, and the LRU over resident
        #: physical rows. Without host_swap these stay empty and
        #: virtual == physical (the historical hard-capped registry).
        self._adapter_rows_host: Dict[int, Any] = {}
        self._adapter_phys: Dict[int, int] = {}
        self._adapter_virt: Dict[int, int] = {}
        self._adapter_lru = hostswap.LRUIndex()
        self._adapter_free_rows: List[int] = (
            list(range(ecfg.adapter_slots - 1, 0, -1))
            if self._lora and self._host_swap else [])
        self._adapter_spills = 0
        self._adapter_pageins = 0
        self._build()
        with expected_compiles():
            # construction compiles (the init programs materialise
            # here) are sanctioned: another live engine's armed
            # recompile guard must read them as a replica being built,
            # not as its own trace-stability breach
            self.cache, self.state = self._init(params)
            if self._chunk_size:
                self._chunk_scratch = self._chunk_scratch_init(params)
            if self._prefix_splits:
                self.pool = self._pool_init(params)
            if self._lora:
                # the adapter pool: zeros everywhere — row 0 IS the
                # pinned base adapter; never donated, so it survives
                # rebuild_slots and fault replay
                self.adapters = self._adapter_init(params)

    @staticmethod
    def _resolve_buckets(ecfg: EngineConfig) -> Tuple[int, ...]:
        buckets = ecfg.prompt_buckets
        if buckets is None:
            return default_prompt_buckets(ecfg.max_prompt_len)
        buckets = tuple(int(b) for b in buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"prompt_buckets must be strictly increasing, got {buckets}")
        if buckets[0] < 1 or buckets[-1] != ecfg.max_prompt_len:
            raise ValueError(
                f"prompt_buckets must lie in [1, max_prompt_len] and end "
                f"at max_prompt_len={ecfg.max_prompt_len} (every prompt "
                f"needs a bucket), got {buckets}")
        return buckets

    @staticmethod
    def _resolve_batch_sizes(ecfg: EngineConfig) -> Tuple[int, ...]:
        sizes = ecfg.admit_batch_sizes
        if sizes is None:
            return tuple(k for k in (1, 2, 4) if k <= ecfg.slots)
        sizes = tuple(int(k) for k in sizes)
        if not sizes or list(sizes) != sorted(set(sizes)):
            raise ValueError(
                f"admit_batch_sizes must be strictly increasing, got {sizes}")
        if sizes[0] != 1:
            raise ValueError(
                f"admit_batch_sizes must start at 1 (the ladder must "
                f"decompose any group count), got {sizes}")
        if sizes[-1] > ecfg.slots:
            raise ValueError(
                f"admit_batch_sizes max {sizes[-1]} exceeds slots "
                f"{ecfg.slots} — a batch cannot outnumber the slots it "
                f"fills")
        return sizes

    @staticmethod
    def _resolve_chunk_ladder(ecfg: EngineConfig) -> Tuple[int, ...]:
        chunks = ecfg.decode_chunks
        if chunks is None:
            return (ecfg.decode_chunk,)
        chunks = tuple(int(c) for c in chunks)
        if not chunks or list(chunks) != sorted(set(chunks)) \
                or chunks[0] < 1:
            raise ValueError(
                f"decode_chunks must be a strictly increasing ladder of "
                f"values >= 1, got {chunks}")
        if ecfg.decode_chunk not in chunks:
            raise ValueError(
                f"decode_chunks {chunks} must contain decode_chunk "
                f"{ecfg.decode_chunk} — the base operating point must "
                f"be a compiled variant")
        return chunks

    @staticmethod
    def _resolve_spec_ladder(ecfg: EngineConfig) -> Tuple[int, ...]:
        ks = ecfg.spec_ks
        if ks is None:
            return (ecfg.spec_k,) if ecfg.spec_k > 0 else ()
        ks = tuple(int(k) for k in ks)
        if not ks or list(ks) != sorted(set(ks)) or ks[0] < 1:
            raise ValueError(
                f"spec_ks must be a strictly increasing ladder of "
                f"values >= 1 (0 — the plain variant — is a tuner "
                f"rung, not a compiled spec program), got {ks}")
        if ecfg.spec_k > 0 and ecfg.spec_k not in ks:
            raise ValueError(
                f"spec_ks {ks} must contain spec_k {ecfg.spec_k} — the "
                f"base operating point must be a compiled variant")
        return ks

    @staticmethod
    def _resolve_prefix_variants(ecfg: EngineConfig,
                                 buckets: Tuple[int, ...]):
        """The prefix pool's static-shape families: usable SPLIT points
        (bucket values that leave >= 1 tail token) and the compiled
        (split, tail bucket) extend variants — a tail bucket is only
        admitted when the combined block ``split + tail_bucket`` fits
        the slot horizon (the tail block is written at offset
        ``split``, and a clamped ``dynamic_update_slice`` would
        silently corrupt a neighbour's columns)."""
        if ecfg.prefix_pool_slots < 0:
            raise ValueError(
                f"prefix_pool_slots {ecfg.prefix_pool_slots} must be "
                f">= 0")
        if ecfg.prefix_pool_slots == 0:
            return (), ()
        mpl = ecfg.max_prompt_len
        splits: List[int] = []
        variants: List[Tuple[int, int]] = []
        for ps in buckets:
            if ps > mpl - 1:
                continue
            tbs = sorted({min(b for b in buckets if b >= tl)
                          for tl in range(1, mpl - ps + 1)})
            tbs = [tb for tb in tbs if ps + tb <= ecfg.max_seq_len]
            if not tbs:
                continue
            splits.append(ps)
            variants.extend((ps, tb) for tb in tbs)
        if not splits:
            raise ValueError(
                f"prefix_pool_slots={ecfg.prefix_pool_slots} but no "
                f"usable split point: no prompt bucket b satisfies "
                f"b <= max_prompt_len-1 with a tail bucket fitting "
                f"max_seq_len (buckets {buckets}, max_prompt_len "
                f"{mpl}, max_seq_len {ecfg.max_seq_len})")
        return tuple(splits), tuple(variants)

    # -- compiled programs -------------------------------------------------

    def _build(self):
        cfg, ecfg, mesh = self.cfg, self.engine_cfg, self._mesh
        pspecs = gpt.param_specs(cfg)
        B = ecfg.slots
        pad = jnp.int32(ecfg.pad_token_id)
        spec = bool(self._spec_ladder)
        self._spec = spec
        # cache [l, 2, B, heads, S, d]: heads are the tp-sharded dim
        # (under a quantized kv_cache_dtype this is the {"kv", "scale"}
        # spec pytree — same sharding on both planes)
        cache_spec = gpt.cache_specs(cfg)
        state_keys = ["tok", "pos", "remaining", "done", "temp",
                      "top_k", "top_p", "key", "eos"]
        if spec:
            state_keys.append("hist")
        state_spec = {k: P() for k in state_keys}

        paged = self._paged
        p_sz = ecfg.page_size
        lora_on = self._lora
        l_scale = self._lora_scale
        lora_spec = gpt.lora_specs(cfg) if lora_on else None

        def init_local(params):
            if paged:
                # the paged pool: the page dim rides the slot dim of
                # the contiguous layout, the horizon dim is one page
                cache = gpt.init_cache(cfg, params, self._num_pages,
                                       max_len=p_sz)
            else:
                cache = gpt.init_cache(cfg, params, B,
                                       max_len=ecfg.max_seq_len)
            state = {
                "tok": jnp.full((B,), pad, jnp.int32),
                "pos": jnp.zeros((B,), jnp.int32),
                "remaining": jnp.zeros((B,), jnp.int32),
                "done": jnp.ones((B,), bool),   # every slot starts free
                "temp": jnp.zeros((B,), jnp.float32),
                "top_k": jnp.zeros((B,), jnp.int32),
                "top_p": jnp.ones((B,), jnp.float32),
                "key": jnp.zeros((B, 2), jnp.uint32),
                "eos": jnp.full((B,), _NO_EOS, jnp.int32),
            }
            if spec:
                # the drafter's token-history ring, -1 = unfilled
                state["hist"] = jnp.full((B, ecfg.spec_hist), -1,
                                         jnp.int32)
            return cache, state

        def make_step_core(chunk: int):
            def step_core(params, cache, state, masks, table, lora):
                # the whole per-token body (decode + per-slot draw +
                # eos/budget masking) lives in gpt.decode_steps — ONE
                # compiled scan of `chunk` steps per dispatch; masks
                # is the per-slot constrained-decoding vocab whitelist
                # (all-True rows are bit-identical to no mask); table
                # is the paged block table (None = contiguous layout);
                # lora is the (adapter pool, [B] id table, scale)
                # bundle (None = no pool — both pool and ids are DATA,
                # one program per variant serves every tenant mix)
                hist = state["hist"] if spec else None
                pos0 = state["pos"]
                cache, state, toks, lps, fins = gpt.decode_steps(
                    cfg, params, cache, state, chunk,
                    pad_token_id=ecfg.pad_token_id, masks=masks,
                    table=table, lora=lora)
                if spec:
                    # keep the drafter's history fresh across PLAIN
                    # chunks too (a payoff-gated or tuner-driven
                    # scheduler flips between the variants): the
                    # chunk's emitted prefix per row is pos_after -
                    # pos_before columns — shift it into the ring so a
                    # later spec chunk drafts from real context
                    state = {**state, "hist": gpt.shift_hist(
                        hist, toks, state["pos"] - pos0)}
                return cache, state, toks, lps, fins

            return step_core

        def make_step_spec_core(chunk: int, k: int):
            def step_spec_core(params, cache, state, masks, table,
                               lora):
                # the speculative chunk: `chunk` draft-verify-accept
                # waves, emitting up to chunk*(k+1) columns (valid
                # marks the real ones); bit-identical streams to the
                # plain variants by the token-matching verification
                # contract (per adapter mix too — the verify forward
                # gathers the same adapter rows the plain path does)
                return gpt.decode_steps_spec(
                    cfg, params, cache, state, chunk,
                    spec_k=k, pad_token_id=ecfg.pad_token_id,
                    masks=masks, table=table, lora=lora)

            return step_spec_core

        def adapt_step(core):
            # core(params, cache, state, masks, table, lora) → the
            # compiled signature for this engine's (paged, lora)
            # feature mix: disabled features contribute NO arguments,
            # so a featureless engine's programs are byte-for-byte the
            # historical ones
            if paged and lora_on:
                def step_local(params, cache, state, masks, table,
                               adapters, aids):
                    return core(params, cache, state, masks, table,
                                (adapters, aids, l_scale))
            elif paged:
                def step_local(params, cache, state, masks, table):
                    return core(params, cache, state, masks, table,
                                None)
            elif lora_on:
                def step_local(params, cache, state, masks, adapters,
                               aids):
                    return core(params, cache, state, masks, None,
                                (adapters, aids, l_scale))
            else:
                def step_local(params, cache, state, masks):
                    return core(params, cache, state, masks, None,
                                None)

            return step_local

        def _parse_extra(extra):
            """Unpack the optional trailing data args every admission
            program shares — (pages, hist0, lora bundle), absent
            features contributing None — so the paged/spec/lora arg
            order is spelled exactly once."""
            i = 0
            pages = hist0 = lora = None
            if paged:
                pages = extra[i]
                i += 1
            if spec:
                hist0 = extra[i]
                i += 1
            if lora_on:
                lora = (extra[i], extra[i + 1], l_scale)
            return pages, hist0, lora

        def make_admit(bucket: int):
            n_ins = -(-bucket // p_sz) if paged else 0

            def admit_local(params, cache, state, slots, prompts, p_lens,
                            max_tokens, temp, top_k, top_p, keys, eos,
                            req_idx, seeded, masks, *extra):
                # extra rides the optional data args in a fixed order:
                # the paged per-row page indices, the spec history
                # seed, then the adapter pool + per-row adapter ids
                pages, hist0, lora = _parse_extra(extra)
                # ONE padded forward admits the whole [k, bucket] batch;
                # row i's logits/KV are exactly its solo prefill_at's
                blocks, logits0 = gpt.prefill_many(
                    cfg, params, prompts, p_lens - 1, max_len=bucket,
                    lora=lora)
                # unseeded rows fold the monotonic request counter into
                # the zero base key ON DEVICE (no host-side compile to
                # trip a recompile guard); seeded rows keep their host
                # key bit-for-bit
                base = jnp.zeros((2,), jnp.uint32)
                folded = jax.vmap(
                    lambda i: jax.random.fold_in(base, i))(req_idx)
                keys = jnp.where(seeded[:, None], keys, folded)
                # the k-row draw_slots call vmaps per row over a
                # [1, vocab] lane — each row IS the solo-generate first
                # draw (same gumbel shape, same fold index)
                first = sampling.draw_slots(
                    logits0, keys, p_lens - 1, temp, top_k, top_p,
                    masks=masks)
                first_lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits0, axis=-1),
                    first[:, None], axis=1)[:, 0]
                if paged:
                    # the paged scatter: row i's bucket columns land
                    # in its own allocated pages (pad columns reach
                    # the sink or the row's not-yet-decoded cells —
                    # masked garbage either way)
                    cache = gpt.cache_insert_pages(
                        cache, _pad_span(blocks, n_ins * p_sz), pages,
                        page_size=p_sz)
                else:
                    cache = gpt.cache_insert_slots(cache, blocks, slots)
                hit_eos = (eos >= 0) & (first == eos)
                done0 = hit_eos | (max_tokens <= 1)
                new_state = {
                    "tok": state["tok"].at[slots].set(first),
                    "pos": state["pos"].at[slots].set(p_lens),
                    "remaining": state["remaining"].at[slots].set(
                        max_tokens - 1),
                    "done": state["done"].at[slots].set(done0),
                    "temp": state["temp"].at[slots].set(temp),
                    "top_k": state["top_k"].at[slots].set(top_k),
                    "top_p": state["top_p"].at[slots].set(top_p),
                    "key": state["key"].at[slots].set(keys),
                    "eos": state["eos"].at[slots].set(eos),
                }
                if spec:
                    # seed the drafter's ring: the prompt tail (packed
                    # host-side — the host knows the full prompt) plus
                    # the first token drawn just above
                    new_state["hist"] = state["hist"].at[slots].set(
                        jnp.concatenate([hist0, first[:, None]],
                                        axis=1))
                return cache, new_state, first, first_lp, hit_eos, done0

            return admit_local

        def retire_local(state, slot):
            return {**state, "done": state["done"].at[slot].set(True)}

        # cache + state are donated: the engine rebinds self.cache /
        # self.state from each call's outputs, and without donation
        # every step/admit copies the whole [l, 2, B, hl, S, d] cache
        # just to update one slot's column (CPU-mesh A/B in
        # docs/DESIGN.md "Serving"; re-measure on chip)
        sm = lambda f, in_specs, out_specs, donate=(): jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False),
            donate_argnums=donate)
        scalar = P()
        n_step_args = 2 if paged else 1  # masks (+ tables)
        # lora args (the adapter pool is tp-sharded — never a scalar
        # spec; the [B]/[k] id tables are) ride LAST on every program
        # that runs a forward
        lora_in = (lora_spec, scalar) if lora_on else ()
        self._init = sm(init_local, (pspecs,), (cache_spec, state_spec))
        if lora_on:
            def adapter_init_local(params):
                return gpt.init_lora_pool(cfg, params,
                                          ecfg.adapter_slots,
                                          ecfg.adapter_rank)

            def adapter_set_local(pool, row, idx):
                return gpt.lora_set_row(pool, row, idx)

            # the pool rides its own init (NOT the slot init): a fault
            # rebuild re-inits slots but leaves registered adapters
            # intact — and the set program is NOT donated, so a failed
            # registration cannot consume the rows already serving
            self._adapter_init = sm(adapter_init_local, (pspecs,),
                                    lora_spec)
            self._adapter_set = sm(
                adapter_set_local,
                (lora_spec, gpt.lora_row_specs(cfg), scalar), lora_spec)
        # one compiled step program per decode-chunk rung, and one
        # spec variant per (chunk, k) cross — a self-tuning scheduler
        # switches among them per dispatch, all pre-warmed, so the
        # armed recompile guard never trips (serving.tuner's contract)
        self._step_variants: Dict[int, Any] = {}
        self._spec_variants: Dict[Tuple[int, int], Any] = {}
        for c in self._chunk_ladder:
            self._step_variants[c] = sm(
                adapt_step(make_step_core(c)),
                (pspecs, cache_spec, state_spec)
                + (scalar,) * n_step_args + lora_in,
                (cache_spec, state_spec, scalar, scalar, scalar),
                donate=(1, 2))
            for k in self._spec_ladder:
                self._spec_variants[(c, k)] = sm(
                    adapt_step(make_step_spec_core(c, k)),
                    (pspecs, cache_spec, state_spec)
                    + (scalar,) * n_step_args + lora_in,
                    (cache_spec, state_spec, scalar, scalar, scalar,
                     scalar),
                    donate=(1, 2))
        # one admission program per (bucket, k) — the k dim and padded
        # width are static shapes, everything request-scoped is data
        # (paged engines thread the per-row page indices, spec engines
        # the host-packed prompt-tail history seed, lora engines the
        # adapter pool + per-row adapter ids)
        n_admit_args = 12 + int(paged) + int(spec)
        self._admits: Dict[Tuple[int, int], Any] = {}
        for bucket in self._buckets:
            fn = make_admit(bucket)
            for k in self._batch_sizes:
                self._admits[(bucket, k)] = sm(
                    fn, (pspecs, cache_spec, state_spec)
                    + (scalar,) * n_admit_args + lora_in,
                    (cache_spec, state_spec, scalar, scalar, scalar,
                     scalar),
                    donate=(1, 2))
        self._retire = sm(retire_local, (state_spec, scalar), state_spec,
                          donate=(0,))

        # -- host-swap tier programs (host_swap=True) ---------------------
        # pages_out gathers n whole pages (storage form — the quantized
        # planes travel as-is, so the host round trip is bit-exact) and
        # pages_in scatters them back; one compiled variant per
        # power-of-two swap-batch rung (plan_rungs decomposes any
        # count), all warmed, both enumerated by _swap_program_items so
        # the recompile sentinel and the flatness pin cover them. The
        # gather does NOT donate (the cache keeps serving); the scatter
        # donates the cache exactly like every other insert.
        self._swap_outs: Dict[int, Any] = {}
        self._swap_ins: Dict[int, Any] = {}
        if self._host_swap:
            def swap_out_local(cache, pages):
                return gpt.cache_gather_pages(cache, pages)

            def swap_in_local(cache, block, pages):
                return gpt.cache_insert_pages(cache, block,
                                              pages[:, None],
                                              page_size=p_sz)

            for n in self._swap_rungs:
                self._swap_outs[n] = sm(
                    swap_out_local, (cache_spec, scalar), cache_spec)
                self._swap_ins[n] = sm(
                    swap_in_local, (cache_spec, cache_spec, scalar),
                    cache_spec, donate=(0,))

            # the resume scatter's state half: write one parked slot's
            # full state row (PRNG key included — the sampled-parity
            # crux) back at a traced slot index, donating state like
            # retire does
            def state_restore_local(state, row, slot):
                return {k: state[k].at[slot].set(row[k][0])
                        for k in state}

            self._state_restore = sm(
                state_restore_local, (state_spec, state_spec, scalar),
                state_spec, donate=(0,))

        # -- chunked-prefill programs (prefill_chunk > 0) -----------------
        # chunk 0 is a bucket-sized cold prefill into the compute-dtype
        # scratch; chunk i attends the scratch's first i*C columns
        # through gpt.prefill_extend (the prefix-reuse forward — cost
        # scales with the chunk, and its hit == cold parity contract
        # makes chunked streams bit-identical to monolithic admission
        # off-TPU); the finish draws the first token from the final
        # chunk's logits and quantizes/inserts the whole prompt block
        # exactly where a cold admission would
        self._chunk_exts: Dict[int, Any] = {}
        if self._chunk_size:
            chunk_c = self._chunk_size
            mpl = ecfg.max_prompt_len
            # the scratch stores COMPUTE-dtype K/V (the pool's
            # master-copy argument: every later chunk must attend the
            # exact prefix values a cold prefill would see;
            # quantization happens once at the finish insert)
            cfg_ext = dataclasses.replace(cfg, kv_cache_dtype="bf16")
            scratch_spec = gpt.cache_specs(cfg_ext)
            n_fin = -(-mpl // p_sz) if paged else 0

            def scratch_init_local(params):
                return gpt.init_cache(cfg_ext, params, 1, max_len=mpl)

            self._chunk_scratch_init = sm(scratch_init_local, (pspecs,),
                                          scratch_spec)

            def chunk0_local(params, scratch, tokens, *extra):
                lora = ((extra[0], extra[1], l_scale) if lora_on
                        else None)
                blocks, _ = gpt.prefill_many(
                    cfg_ext, params, tokens,
                    jnp.full((1,), chunk_c - 1, jnp.int32),
                    max_len=chunk_c, lora=lora)
                return gpt.cache_insert_slot(scratch, blocks,
                                             jnp.int32(0))

            self._chunk0 = sm(chunk0_local,
                              (pspecs, scratch_spec, scalar) + lora_in,
                              scratch_spec, donate=(1,))

            def make_chunk_ext(i: int):
                pfx = i * chunk_c

                def chunk_ext_local(params, scratch, tail, last,
                                    *extra):
                    lora = ((extra[0], extra[1], l_scale) if lora_on
                            else None)
                    prefix = jax.tree.map(
                        lambda x: lax.slice_in_dim(x, 0, pfx, axis=4),
                        scratch)
                    tail_kv, logits = gpt.prefill_extend(
                        cfg, params, prefix, tail, last,
                        prefix_len=pfx, lora=lora)
                    return (gpt.cache_insert_slot(
                        scratch, tail_kv, jnp.int32(0), pos=pfx),
                        logits)

                return chunk_ext_local

            for i in range(1, mpl // chunk_c):
                self._chunk_exts[i] = sm(
                    make_chunk_ext(i),
                    (pspecs, scratch_spec, scalar, scalar) + lora_in,
                    (scratch_spec, scalar), donate=(1,))

            def chunk_finish_local(params, cache, state, scratch,
                                   logits0, slots, p_lens, max_tokens,
                                   temp, top_k, top_p, keys, eos,
                                   req_idx, seeded, masks, *extra):
                pages = extra[0] if paged else None
                hist0 = extra[-1] if spec else None
                base = jnp.zeros((2,), jnp.uint32)
                folded = jax.vmap(
                    lambda i: jax.random.fold_in(base, i))(req_idx)
                keys = jnp.where(seeded[:, None], keys, folded)
                # the fold position is p_len - 1, exactly the cold
                # admission's — same logits (prefill_extend parity),
                # same fold, same first draw
                first = sampling.draw_slots(
                    logits0, keys, p_lens - 1, temp, top_k, top_p,
                    masks=masks)
                first_lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits0, axis=-1),
                    first[:, None], axis=1)[:, 0]
                blk = gpt.quantize_cache_block(cfg, scratch)
                if paged:
                    cache = gpt.cache_insert_pages(
                        cache, _pad_span(blk, n_fin * p_sz), pages,
                        page_size=p_sz)
                else:
                    cache = gpt.cache_insert_slot(cache, blk, slots[0])
                hit_eos = (eos >= 0) & (first == eos)
                done0 = hit_eos | (max_tokens <= 1)
                new_state = {
                    "tok": state["tok"].at[slots].set(first),
                    "pos": state["pos"].at[slots].set(p_lens),
                    "remaining": state["remaining"].at[slots].set(
                        max_tokens - 1),
                    "done": state["done"].at[slots].set(done0),
                    "temp": state["temp"].at[slots].set(temp),
                    "top_k": state["top_k"].at[slots].set(top_k),
                    "top_p": state["top_p"].at[slots].set(top_p),
                    "key": state["key"].at[slots].set(keys),
                    "eos": state["eos"].at[slots].set(eos),
                }
                if spec:
                    new_state["hist"] = state["hist"].at[slots].set(
                        jnp.concatenate([hist0, first[:, None]],
                                        axis=1))
                return (cache, new_state, first, first_lp, hit_eos,
                        done0)

            self._chunk_finish = sm(
                chunk_finish_local,
                (pspecs, cache_spec, state_spec, scratch_spec)
                + (scalar,) * (12 + int(paged) + int(spec)),
                (cache_spec, state_spec, scalar, scalar, scalar,
                 scalar),
                donate=(1, 2))

        # -- shared-prefix pool programs (prefix_pool_slots > 0) ----------
        self._pool_inserts: Dict[int, Any] = {}
        self._pool_pageins: Dict[int, Any] = {}
        self._admit_prefix: Dict[Tuple[int, int], Any] = {}
        if not self._prefix_splits:
            return
        pool_pages = ecfg.prefix_pool_slots
        pool_horizon = max(self._prefix_splits)
        # the pool stores COMPUTE-dtype K/V even under a quantized
        # kv_cache_dtype — the amp master-copy idea: the tail-extend
        # forward attends over the EXACT prefix values (what a cold
        # prefill of the full prompt would see), and quantization
        # happens once at slot insert, exactly where the cold path
        # quantizes. A quantized pool would make hits attend over
        # dequantize(quantize(prefix)) while cold admissions attend
        # over the exact prefix — a quantization-error divergence the
        # bit-parity oracle would only catch when a token lands near a
        # tie. The pool is tiny next to the slot cache; the capacity
        # play is the slots.
        cfg_pool = dataclasses.replace(cfg, kv_cache_dtype="bf16")
        pool_spec = gpt.cache_specs(cfg_pool)

        def pool_init_local(params):
            return gpt.init_cache(cfg_pool, params, pool_pages,
                                  max_len=pool_horizon)

        # the pool rides its own init (NOT the slot init): a fault
        # rebuild re-inits slots but leaves registered prefixes intact
        self._pool_init = sm(pool_init_local, (pspecs,), pool_spec)

        def make_pool_insert(pb: int):
            def pool_insert_local(params, pool, tokens, page):
                # the whole [1, pb] prefix is real — register slices
                # the template AT the bucket — so every stored K/V
                # position is valid for any prompt sharing it
                blocks, _ = gpt.prefill_many(
                    cfg_pool, params, tokens,
                    jnp.full((1,), pb - 1, jnp.int32), max_len=pb)
                return gpt.cache_insert_slot(pool, blocks, page)

            return pool_insert_local

        for pb in self._prefix_splits:
            self._pool_inserts[pb] = sm(
                make_pool_insert(pb),
                (pspecs, pool_spec, scalar, scalar), pool_spec,
                donate=(1,))

        if paged:
            # the copy-on-write page-in: quantize a registered
            # prefix's compute-dtype pool block ONCE into pinned cache
            # pages (the same quantizer, same input values as a cold
            # prefill of those positions — so a page-sharing hit reads
            # bit-identical cache bytes to a PR-7 pooled-slot copy).
            # Hits then map these pages read-only; no prefix K/V bytes
            # move at admission time at all.
            def make_pool_pagein(pb: int):
                def pool_pagein_local(cache, pool, page, pages):
                    block = gpt.cache_gather_page(pool, page, pb)
                    return gpt.cache_insert_pages(
                        cache, gpt.quantize_cache_block(cfg, block),
                        pages, page_size=p_sz)

                return pool_pagein_local

            for pb in self._prefix_splits:
                self._pool_pageins[pb] = sm(
                    make_pool_pagein(pb),
                    (cache_spec, pool_spec, scalar, scalar), cache_spec,
                    donate=(0,))

        def make_admit_prefix(ps: int, tb: int):
            n_tail = -(-tb // p_sz) if paged else 0

            def admit_prefix_local(params, cache, state, pool, slots,
                                   tails, t_lens, max_tokens, temp,
                                   top_k, top_p, keys, eos, req_idx,
                                   seeded, masks, page, *extra):
                pages, hist0, lora = _parse_extra(extra)
                # the compiled gather: page -> [l, 2, 1, hl, ps, d]
                # block of EXACT compute-dtype prefix K/V (the pool's
                # master copy). Prefix hits are validated to ride the
                # BASE adapter (id 0 — the pooled prefix was prefilled
                # with base weights), so the threaded lora bundle is
                # an exact zero delta; it rides anyway so the program
                # signature is uniform across the lora engine's
                # admission family.
                block = gpt.cache_gather_page(pool, page, ps)
                tail_kv, logits0 = gpt.prefill_extend(
                    cfg, params, block, tails, t_lens - 1,
                    prefix_len=ps, lora=lora)
                base = jnp.zeros((2,), jnp.uint32)
                folded = jax.vmap(
                    lambda i: jax.random.fold_in(base, i))(req_idx)
                keys = jnp.where(seeded[:, None], keys, folded)
                p_lens = ps + t_lens
                first = sampling.draw_slots(
                    logits0, keys, p_lens - 1, temp, top_k, top_p,
                    masks=masks)
                first_lp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits0, axis=-1),
                    first[:, None], axis=1)[:, 0]
                if paged:
                    # copy-on-write: the prefix pages are SHARED (the
                    # host mapped them into this slot's table row and
                    # pinned their refcounts) — only the TAIL block
                    # moves, into the slot's private pages at the
                    # page-aligned split offset. The shared pages
                    # already hold quantize(prefix) from registration
                    # page-in, so the slot's gathered cache bytes are
                    # exactly what the contiguous two-insert spelling
                    # below produces.
                    cache = gpt.cache_insert_pages(
                        cache,
                        _pad_span(gpt.quantize_cache_block(cfg, tail_kv),
                                  n_tail * p_sz),
                        pages, page_size=p_sz)
                else:
                    # the prefix block quantizes at INSERT (same
                    # quantizer, same exact input values as a cold
                    # prefill of those positions), the tail block
                    # appends at offset ps — together exactly the
                    # cache bytes a cold admission of the full prompt
                    # would hold
                    cache = gpt.cache_insert_slot(
                        cache, gpt.quantize_cache_block(cfg, block),
                        slots[0])
                    cache = gpt.cache_insert_slot(
                        cache, gpt.quantize_cache_block(cfg, tail_kv),
                        slots[0], pos=ps)
                hit_eos = (eos >= 0) & (first == eos)
                done0 = hit_eos | (max_tokens <= 1)
                new_state = {
                    "tok": state["tok"].at[slots].set(first),
                    "pos": state["pos"].at[slots].set(p_lens),
                    "remaining": state["remaining"].at[slots].set(
                        max_tokens - 1),
                    "done": state["done"].at[slots].set(done0),
                    "temp": state["temp"].at[slots].set(temp),
                    "top_k": state["top_k"].at[slots].set(top_k),
                    "top_p": state["top_p"].at[slots].set(top_p),
                    "key": state["key"].at[slots].set(keys),
                    "eos": state["eos"].at[slots].set(eos),
                }
                if spec:
                    new_state["hist"] = state["hist"].at[slots].set(
                        jnp.concatenate([hist0, first[:, None]],
                                        axis=1))
                return (cache, new_state, first, first_lp, hit_eos,
                        done0)

            return admit_prefix_local

        for (ps, tb) in self._extend_variants:
            self._admit_prefix[(ps, tb)] = sm(
                make_admit_prefix(ps, tb),
                (pspecs, cache_spec, state_spec, pool_spec)
                + (scalar,) * (13 + int(paged) + int(spec)) + lora_in,
                (cache_spec, state_spec, scalar, scalar, scalar,
                 scalar),
                donate=(1, 2))

    # -- host API ----------------------------------------------------------

    @property
    def slots(self) -> int:
        return self.engine_cfg.slots

    @property
    def prompt_buckets(self) -> Tuple[int, ...]:
        """The resolved padded-prefill length ladder (ascending; ends
        at ``max_prompt_len``)."""
        return self._buckets

    @property
    def admit_batch_sizes(self) -> Tuple[int, ...]:
        """The resolved admission batch-size ladder (ascending; starts
        at 1)."""
        return self._batch_sizes

    @property
    def decode_chunks(self) -> Tuple[int, ...]:
        """The resolved decode-chunk step-variant ladder (ascending;
        always contains the base ``decode_chunk``) — every rung is one
        pre-warmed compiled step program a tuner may dispatch."""
        return self._chunk_ladder

    @property
    def spec_ks(self) -> Tuple[int, ...]:
        """The resolved speculative draft-width ladder (ascending;
        empty = no speculation) — every rung crosses with every
        decode-chunk rung as one pre-warmed spec step program."""
        return self._spec_ladder

    @property
    def prefix_pool_enabled(self) -> bool:
        """True when ``EngineConfig.prefix_pool_slots > 0`` resolved to
        at least one usable split point."""
        return bool(self._prefix_splits)

    @property
    def prefix_splits(self) -> Tuple[int, ...]:
        """Bucket-aligned split points the prefix pool can reuse at
        (ascending; empty when the pool is disabled)."""
        return self._prefix_splits

    # -- paged KV cache (EngineConfig.page_size > 0) -----------------------

    @property
    def paged(self) -> bool:
        """True when the cache runs the paged layout."""
        return self._paged

    @property
    def page_allocator(self) -> Optional[PageAllocator]:
        """The refcounted page allocator (None in contiguous mode) —
        the scheduler's occupancy/fragmentation gauge source."""
        return self._page_alloc

    @property
    def max_pages(self) -> int:
        """Block-table width per slot (``ceil(max_seq_len /
        page_size)`` — a config-derived constant; 0 in contiguous
        mode)."""
        return self._max_pages

    def pages_needed(self, prompt_len: int, max_tokens: int,
                     prefix_len: int = 0) -> int:
        """Private pages one admission pins: the request's token
        footprint (prompt + budget, minus a shared prefix) in pages.
        0 in contiguous mode — the scheduler's backpressure check is
        layout-agnostic."""
        if not self._paged:
            return 0
        p = self.engine_cfg.page_size
        return -(-(prompt_len + max_tokens) // p) - prefix_len // p

    def can_admit_pages(self, prompt_len: int, max_tokens: int,
                        prefix_len: int = 0) -> bool:
        """Whether the pool currently has the private pages this
        admission needs (always True in contiguous mode)."""
        if not self._paged:
            return True
        return self._page_alloc.can_alloc(
            self.pages_needed(prompt_len, max_tokens, prefix_len))

    def free_slot(self, slot: int) -> None:
        """Release ``slot``'s page mapping: private pages return to
        the free list, shared prefix pages drop one pin, and the
        slot's table row redirects to the sink page (its frozen decode
        lane keeps writing every chunk — the sink absorbs that). The
        scheduler calls this at request release; no-op in contiguous
        mode (slots there are implicitly recycled by the next
        admission's overwrite)."""
        if self._paged:
            self._free_slot_pages(slot)
        if self._host_swap and self._lora:
            # unpin the slot's adapter row so the paging LRU can spill
            # it (done lanes emit pad regardless of the row they read,
            # so rebinding a freed slot to base is stream-invisible)
            self._set_slot_adapter(slot, 0)

    def page_stats(self) -> Optional[Dict[str, float]]:
        """Allocator occupancy snapshot (None in contiguous mode)."""
        if self._page_alloc is None:
            return None
        return self._page_alloc.stats()

    def _free_slot_pages(self, slot: int) -> None:
        ent = self._slot_pages.pop(slot, None)
        if ent is None:
            return
        priv, shared, footprint = ent
        self._page_alloc.free(priv)
        self._page_alloc.free(shared)
        self._page_alloc.used_tokens -= footprint
        self._tables[slot, :] = SINK
        self._tables_dev = None

    def _alloc_slot_pages(self, slot: int, p_len: int, max_tokens: int,
                          prefix_page: Optional[int] = None,
                          prefix_len: int = 0) -> np.ndarray:
        """Map ``slot``'s table row for one admission: pin the shared
        prefix pages (copy-on-write — refcount, no bytes move),
        allocate the private tail/decode pages, sink-fill the rest.
        Raises :class:`PagesExhausted` (before any state change beyond
        releasing the slot's stale mapping) when the pool is dry.
        Returns the row."""
        self._free_slot_pages(slot)
        p = self.engine_cfg.page_size
        shared: List[int] = []
        if prefix_page is not None:
            shared = list(
                self._prefix_pages[prefix_page][:prefix_len // p])
        need = -(-(p_len + max_tokens) // p) - len(shared)
        priv = self._page_alloc.alloc(need)
        self._page_alloc.share(shared)
        row = np.full((self._max_pages,), SINK, np.int32)
        row[:len(shared)] = shared
        row[len(shared):len(shared) + need] = priv
        self._tables[slot] = row
        self._tables_dev = None
        footprint = p_len + max_tokens - prefix_len
        self._page_alloc.used_tokens += footprint
        self._slot_pages[slot] = (priv, shared, footprint)
        return self._tables[slot]

    # -- host-swap tier (EngineConfig.host_swap) ---------------------------

    @property
    def host_swap_enabled(self) -> bool:
        """True when ``EngineConfig.host_swap`` is on."""
        return self._host_swap

    def host_parked(self, key: Any) -> bool:
        """Whether ``key``'s swap payload is still in the host tier
        (False after a capacity eviction — the recompute-fallback
        signal)."""
        return (self._host_tier is not None
                and key in self._host_tier)

    def swap_in_cost_s(self, n_pages: int) -> Optional[float]:
        """Measured swap-in wall cost for ``n_pages`` (the per-page
        EWMA the auto resume policy prices against replay); ``None``
        before the first measured resume."""
        if self._swap_in_ewma_s <= 0.0:
            return None
        return self._swap_in_ewma_s * max(n_pages, 1)

    def host_tier_stats(self) -> Optional[Dict[str, float]]:
        """Host-tier occupancy snapshot (None without host_swap)."""
        if self._host_tier is None:
            return None
        return self._host_tier.stats()

    def parked_pages(self, key: Any) -> int:
        """Private pages ``key``'s parked payload holds (0 when not
        swap-parked) — what a swap-resume must allocate."""
        if self._host_tier is None:
            return 0
        ent = self._host_tier._entries.get(key)
        return 0 if ent is None else ent.n_pages

    def parked_bytes(self, key: Any) -> int:
        """Host-RAM bytes ``key``'s parked payload holds (0 when not
        swap-parked) — the ``page_swap_out`` flight event's byte
        field."""
        if self._host_tier is None:
            return 0
        ent = self._host_tier._entries.get(key)
        return 0 if ent is None else ent.nbytes

    def slot_page_count(self, slot: int) -> int:
        """PRIVATE pages ``slot``'s live mapping holds (0 when
        unmapped, or in contiguous mode) — what preempting the slot
        would free back to the pool."""
        if not self._paged:
            return 0
        ent = self._slot_pages.get(slot)
        return 0 if ent is None else len(ent[0])

    def park_slot(self, slot: int, key: Any) -> List[Any]:
        """Swap ``slot`` out to the host tier under ``key``: gather its
        PRIVATE pages (compiled per-rung ``pages_out`` — storage form,
        bit-exact round trip) and its full state row (PRNG key
        included) into a host payload, retire the lane, free the
        device pages, and park the payload in the LRU. Shared
        copy-on-write prefix pages never move — they drop the slot's
        pin here and re-pin at resume (the registration pin keeps them
        alive and :meth:`rebuild_slots` re-pages them into the same
        ids, so a parked conversation even survives a fault rebuild).

        Returns the keys the tier capacity-evicted to make room
        (possibly including ``key`` itself) — the caller downgrades
        those conversations to recompute-resume; their page/byte
        accounting is dropped here. The caller must ensure no chunk is
        in flight (parking never happens mid-chunk — the dispatched
        tables still map the pages being freed)."""
        self._check_poisoned()
        if not self._host_swap:
            raise ValueError(
                "park_slot without host_swap (EngineConfig.host_swap "
                "== False)")
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} outside [0, {self.slots})")
        ent = self._slot_pages.get(slot)
        if ent is None:
            raise ValueError(
                f"slot {slot} has no page mapping — nothing to park")
        priv, shared, footprint = ent
        # the state row FIRST (retire below flips its done flag)
        row = {k: np.asarray(self.state[k])[slot:slot + 1].copy()
               for k in self.state}
        blocks: List[Tuple[int, Any]] = []
        off = 0
        for n in hostswap.plan_rungs(len(priv)):
            chunk = np.asarray(priv[off:off + n], np.int32)
            blocks.append((n, jax.tree.map(
                np.asarray, self._swap_outs[n](self.cache, chunk))))
            off += n
        nbytes = int(sum(x.nbytes for _, b in blocks
                         for x in jax.tree.leaves(b)))
        payload = {
            "blocks": blocks, "state": row, "shared": list(shared),
            "n_priv": len(priv), "footprint": footprint,
            "mask": self._masks[slot].copy(),
            "adapter": int(self._adapter_virtual(
                int(self._adapter_ids[slot]))),
        }
        # freeze the lane, then release its device footprint: the
        # table row redirects to the sink, so the frozen column's
        # writes land in garbage
        self.retire(slot)
        self._free_slot_pages(slot)
        self._page_alloc.note_swap_out(len(priv), nbytes)
        evicted = self._host_tier.park(key, payload, len(priv), nbytes)
        out: List[Any] = []
        for ek, e in evicted:
            self._page_alloc.note_swap_drop(e.n_pages, e.nbytes)
            out.append(ek)
        return out

    def resume_slot(self, slot: int, key: Any) -> None:
        """Swap ``key``'s parked conversation back into ``slot``:
        allocate fresh private pages (:class:`PagesExhausted`
        propagates BEFORE any device work — check
        ``page_allocator.can_alloc(parked_pages(key))`` first), re-pin
        its shared prefix pages, scatter the host payload through the
        per-rung ``pages_in`` programs, and restore the state row /
        vocab mask / adapter binding. The continued stream is
        bit-identical to an uninterrupted run (the restored PRNG key
        and token-history ring carry the sampled path). Raises
        ``KeyError`` when the payload was capacity-evicted — the
        caller's recompute fallback."""
        self._check_poisoned()
        if not self._host_swap:
            raise ValueError(
                "resume_slot without host_swap (EngineConfig.host_swap "
                "== False)")
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} outside [0, {self.slots})")
        if slot in self._slot_pages:
            raise ValueError(
                f"slot {slot} still holds a page mapping — free it "
                f"before resuming into it")
        if not self.host_parked(key):
            raise KeyError(
                f"{key!r} has no host payload (capacity-evicted or "
                f"never swap-parked) — resume by recompute")
        t0 = time.perf_counter()
        ent = self._host_tier.take(key)
        p = ent.payload
        n_priv, shared = p["n_priv"], p["shared"]
        priv = self._page_alloc.alloc(n_priv)
        self._page_alloc.share(shared)
        tab = np.full((self._max_pages,), SINK, np.int32)
        tab[:len(shared)] = shared
        tab[len(shared):len(shared) + n_priv] = priv
        self._tables[slot] = tab
        self._tables_dev = None
        self._page_alloc.used_tokens += p["footprint"]
        self._slot_pages[slot] = (priv, list(shared), p["footprint"])
        try:
            off = 0
            for n, block in p["blocks"]:
                self.cache = self._swap_ins[n](
                    self.cache, block,
                    np.asarray(priv[off:off + n], np.int32))
                off += n
            self.state = self._state_restore(self.state, p["state"],
                                             np.int32(slot))
        except Exception:
            # the scatter DONATES cache/state — a failure may have
            # consumed them; poison until rebuild_slots() like every
            # other donating seam (the payload is already consumed, so
            # the caller falls back to recompute)
            self._free_slot_pages(slot)
            self._poisoned = True
            raise
        if not np.array_equal(self._masks[slot], p["mask"]):
            self._masks[slot] = p["mask"]
            self._masks_dev = None
        self._bind_slot_adapter(slot, p["adapter"])
        self._page_alloc.note_swap_in(n_priv, ent.nbytes)
        # sync via value fetch (never block_until_ready) so the EWMA
        # prices the whole round trip the auto policy compares
        np.asarray(self.state["tok"])
        sample = (time.perf_counter() - t0) / max(n_priv, 1)
        self._swap_in_ewma_s = (
            sample if self._swap_in_ewma_s <= 0.0
            else 0.7 * self._swap_in_ewma_s + 0.3 * sample)

    def drop_parked(self, key: Any) -> None:
        """Discard ``key``'s swap payload (a recompute-resume or an
        expired parked conversation) — accounting only, no device
        work. No-op when absent."""
        if self._host_tier is None:
            return
        ent = self._host_tier.take(key)
        if ent is not None:
            self._page_alloc.note_swap_drop(ent.n_pages, ent.nbytes)

    def register_prefix(self, tokens) -> int:
        """Prefill a shared prompt prefix (a system-prompt template)
        ONCE into a pool page; returns the page index. The template is
        sliced AT its largest usable split bucket (every stored K/V
        position is real), and indexed at every smaller split too, so
        :meth:`match_prefix` can reuse the longest bucket-aligned
        piece a prompt shares. Registering a template whose
        bucket-aligned slice is already pooled returns the existing
        page (no device work). Raises when the pool is disabled, full,
        or the template is shorter than the smallest split bucket.
        Call AFTER :meth:`warmup` (which resets the pool); the insert
        rides a program warmup already compiled, so a recompile guard
        stays armed through registration."""
        if not self._prefix_splits:
            raise ValueError(
                "prefix pool disabled (EngineConfig.prefix_pool_slots "
                "== 0)")
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 1 or tokens.size < 1:
            raise ValueError("prefix template must be a 1-D token list")
        if tokens.min() < 0 or tokens.max() >= self.cfg.vocab_size:
            raise ValueError(
                f"prefix template tokens outside vocab "
                f"[0, {self.cfg.vocab_size})")
        usable = [b for b in self._prefix_splits if b <= tokens.size]
        if not usable:
            raise ValueError(
                f"prefix template of {tokens.size} tokens is shorter "
                f"than the smallest split bucket "
                f"{self._prefix_splits[0]} — nothing to pool")
        pb = max(usable)
        t = tuple(int(x) for x in tokens[:pb])
        hit = self._prefix_index.get(t)
        if hit is not None and hit[1] == pb:
            return hit[0]
        if self._prefix_used >= self.engine_cfg.prefix_pool_slots:
            raise ValueError(
                f"prefix pool full "
                f"({self.engine_cfg.prefix_pool_slots} pages)")
        page = self._prefix_used
        try:
            self.pool = self._pool_inserts[pb](
                self._params, self.pool,
                np.asarray([t], np.int32), np.int32(page))
        except Exception:
            # the insert DONATES the pool buffer: an error escaping the
            # call may have consumed it, and every already-registered
            # page lives inside it — reset pool + registry to a clean
            # empty state (callers re-register) rather than leave the
            # index pointing into a dead buffer
            self._prefix_index.clear()
            self._prefix_tokens.clear()
            self._prefix_used = 0
            self.pool = self._pool_init(self._params)
            raise
        if self._paged:
            # page-in the quantized prefix ONCE into pinned cache
            # pages — the copy-on-write master every sharing hit maps
            # read-only (refcount 1 here = the registration pin, so
            # the pages survive every hit's release)
            cache_pages = self._page_alloc.alloc(
                pb // self.engine_cfg.page_size)
            try:
                self.cache = self._pool_pageins[pb](
                    self.cache, self.pool, np.int32(page),
                    np.asarray([cache_pages], np.int32))
            except Exception:
                # the page-in DONATES the cache — a failure may have
                # consumed it; poison until rebuild_slots() like every
                # other cache-donating seam
                self._page_alloc.free(cache_pages)
                self._poisoned = True
                raise
            self._prefix_pages[page] = cache_pages
            self._page_alloc.used_tokens += pb
        # page committed only after the insert landed — a failed call
        # must not leak the page
        self._prefix_used += 1
        self._prefix_tokens[page] = t
        for b in usable:
            # first registration wins a shorter shared key — the K/V
            # of tokens[:b] is identical whichever template stored it
            self._prefix_index.setdefault(t[:b], (page, b))
        return page

    def match_prefix(self, prompt) -> Optional[Tuple[int, int]]:
        """Longest-split prefix-pool hit for ``prompt``: returns
        ``(page, split)`` such that ``prompt[:split]`` equals a pooled
        prefix, ``split`` is bucket-aligned, at least one tail token
        remains, and a compiled (split, tail bucket) extend variant
        exists — or ``None`` (cold prefill). O(splits) tuple-hash
        lookups; no device work."""
        if not self._prefix_index:
            return None
        t = tuple(int(x) for x in prompt)
        for split in sorted(self._prefix_splits, reverse=True):
            if split >= len(t):
                continue
            tb = self.bucket_for(len(t) - split)
            if (split, tb) not in self._admit_prefix:
                continue
            hit = self._prefix_index.get(t[:split])
            if hit is not None:
                return hit[0], split
        return None

    # -- batched multi-LoRA (EngineConfig.adapter_slots > 0) ---------------

    @property
    def adapter_pool_enabled(self) -> bool:
        """True when ``EngineConfig.adapter_slots > 0``."""
        return self._lora

    @property
    def adapter_names(self) -> Dict[str, int]:
        """Registered adapter name → pool row (copy; excludes the
        pinned base row 0) — the ``/v1/models`` listing source."""
        return dict(self._adapter_names)

    @property
    def adapters_registered(self) -> int:
        """Registered adapter count (excluding the pinned base
        row)."""
        return max(self._adapter_used - 1, 0)

    def adapter_bytes(self) -> int:
        """Device bytes held by the adapter pool (0 when disabled)."""
        if self.adapters is None:
            return 0
        return int(sum(x.nbytes
                       for x in jax.tree.leaves(self.adapters)))

    def _lora_expected_shapes(self) -> Dict[str, Dict[str, Tuple[int, ...]]]:
        cfg, r = self.cfg, self.engine_cfg.adapter_rank
        L, h, f = cfg.num_layers, cfg.hidden_size, cfg.ffn
        return {
            "qkv": {"a": (L, r, h), "b": (L, r, 3, h)},
            "proj": {"a": (L, r, h), "b": (L, r, h)},
            "fc1": {"a": (L, r, h), "b": (L, r, f)},
            "fc2": {"a": (L, r, f), "b": (L, r, h)},
        }

    def register_adapter(self, weights=None, *, name: Optional[str] = None,
                         seed: Optional[int] = None) -> int:
        """Register one LoRA adapter into the next free pool row;
        returns its id (the value requests pass as
        ``Admission.adapter`` / ``Request.adapter``). Either pass
        ``weights`` — GLOBAL per-site ``{"qkv"/"proj"/"fc1"/"fc2":
        {"a", "b"}}`` arrays in the :func:`gpt.init_lora_weights`
        layout — or ``seed`` to generate the deterministic synthetic
        adapter that seed names (the bench/demo path; post-mortem
        replay rebuilds seeded adapters bit-identically from the
        recorded seed). Registering an already-registered ``name``
        returns the existing id (idempotent, like
        :meth:`register_prefix`). Call AFTER :meth:`warmup` — the set
        program is compiled there, so registration never trips an
        armed recompile guard. The pool is never donated: registered
        rows survive :meth:`rebuild_slots` and fault replay."""
        if not self._lora:
            raise ValueError(
                "adapter pool disabled (EngineConfig.adapter_slots "
                "== 0)")
        if not self._warmed:
            raise ValueError(
                "register_adapter() before warmup(): the adapter-set "
                "program compiles during warmup — call warmup() "
                "first, then register (the prefix-pool lifecycle)")
        if (weights is None) == (seed is None):
            raise ValueError(
                "pass exactly one of weights= or seed=")
        if name is None:
            name = (f"adapter-seed-{seed}" if seed is not None
                    else f"adapter-{self._adapter_used}")
        hit = self._adapter_names.get(name)
        if hit is not None:
            return hit
        if seed is not None:
            weights = gpt.init_lora_weights(
                self.cfg, self.engine_cfg.adapter_rank, seed)
        # validate the payload BEFORE the capacity check: a malformed
        # adapter should fail as malformed whether or not the pool
        # happens to be full
        expected = self._lora_expected_shapes()
        row: Dict[str, Dict[str, np.ndarray]] = {}
        for site, parts in expected.items():
            if site not in weights:
                raise ValueError(f"adapter weights missing site "
                                 f"{site!r}")
            row[site] = {}
            for part, shape in parts.items():
                arr = np.asarray(weights[site][part], np.float32)
                if arr.shape != shape:
                    raise ValueError(
                        f"adapter {site}.{part} shape {arr.shape} != "
                        f"expected {shape} (rank/layers/hidden are "
                        f"compile-time static — ADAPTER-STATIC)")
                row[site][part] = arr
        if self._host_swap:
            # paged registry: ids are LOGICAL (no cap — hundreds of
            # registrations against a static pool); the row lives in
            # the host registry and pages into a physical pool row at
            # admission (immediately while free rows remain, so the
            # under-capacity path matches the historical engine)
            idx = self._adapter_used
            self._adapter_rows_host[idx] = row
            self._adapter_used += 1
            if self._adapter_free_rows:
                try:
                    self._adapter_physical(idx)
                except Exception:
                    self._adapter_rows_host.pop(idx, None)
                    self._adapter_used -= 1
                    raise
            self._adapter_names[name] = idx
            self._adapter_meta[idx] = {
                "id": idx, "name": name, "seed": seed,
                "rank": self.engine_cfg.adapter_rank}
            return idx
        if self._adapter_used >= self.engine_cfg.adapter_slots:
            raise ValueError(
                f"adapter pool full ({self.engine_cfg.adapter_slots} "
                f"rows incl. the pinned base row 0)")
        idx = self._adapter_used
        # NOT donated: a failed set leaves every serving row intact
        self.adapters = self._adapter_set(self.adapters, row,
                                          np.int32(idx))
        self._adapter_used += 1
        self._adapter_names[name] = idx
        self._adapter_meta[idx] = {"id": idx, "name": name,
                                   "seed": seed,
                                   "rank": self.engine_cfg.adapter_rank}
        return idx

    def describe(self) -> Dict[str, Any]:
        """JSON-safe snapshot of everything needed to REBUILD this
        engine elsewhere — the post-mortem bundle's ``config.json``
        (``apex_tpu.telemetry.replay`` reconstructs the GPTConfig /
        EngineConfig / prefix templates from it). Dtypes serialise by
        numpy name (``compute_dtype: "float32"``); anything else
        non-primitive falls back to ``str`` (reported, not
        replayable)."""
        model: Dict[str, Any] = {}
        for f in dataclasses.fields(self.cfg):
            v = getattr(self.cfg, f.name)
            if not isinstance(v, (int, float, str, bool, type(None))):
                try:  # dtype-valued fields (compute_dtype, param_dtype)
                    v = np.dtype(v).name
                except TypeError:
                    v = str(v)
            model[f.name] = v
        return {
            "model": model,
            "engine": dataclasses.asdict(self.engine_cfg),
            "tp": int(self._mesh.shape.get("tp", 1)),
            "prompt_buckets": list(self._buckets),
            "admit_batch_sizes": list(self._batch_sizes),
            "decode_chunks": list(self._chunk_ladder),
            "spec_ks": list(self._spec_ladder),
            "prefix_templates": [list(self._prefix_tokens[p])
                                 for p in sorted(self._prefix_tokens)],
            # seeded registrations replay bit-identically (the seed
            # regenerates the exact weights); explicit-weight ones
            # record seed=None and replay skips their requests
            "adapters": [dict(self._adapter_meta[i])
                         for i in sorted(self._adapter_meta)],
            "warmed": self._warmed,
            "poisoned": self._poisoned,
        }

    def cache_bytes(self) -> int:
        """Device bytes held by the slot KV cache — under a quantized
        ``kv_cache_dtype`` the int8/fp8 data plane plus the fp32 scale
        plane (the capacity number the quantization exists to shrink).
        Shape/dtype metadata only; no transfer."""
        return int(sum(x.nbytes for x in jax.tree.leaves(self.cache)))

    def pool_bytes(self) -> int:
        """Device bytes held by the shared-prefix pool (0 when
        disabled)."""
        if self.pool is None:
            return 0
        return int(sum(x.nbytes for x in jax.tree.leaves(self.pool)))

    def bucket_for(self, prompt_len: int) -> int:
        """The smallest prefill bucket that fits ``prompt_len``."""
        for b in self._buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds max_prompt_len "
            f"{self.engine_cfg.max_prompt_len}")

    def pad_prompt(self, prompt, length: Optional[int] = None) -> np.ndarray:
        """Right-pad ``prompt`` (1-D ints) to ``length`` (default
        ``max_prompt_len``), validating its length — the static
        admission shape of one bucket."""
        length = self.engine_cfg.max_prompt_len if length is None else length
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or not 1 <= prompt.size <= length:
            raise ValueError(
                f"prompt must be 1-D with 1..{length}"
                f" tokens, got shape {prompt.shape}")
        out = np.full((length,), self.engine_cfg.pad_token_id, np.int32)
        out[:prompt.size] = prompt
        return out

    def _validate_admission(self, a: Admission) -> Tuple[np.ndarray, int]:
        """Shared per-request admission validation; returns the raw
        prompt array and its length (padding happens per group, once
        the group's bucket is known)."""
        if not 0 <= a.slot < self.slots:
            raise ValueError(
                f"slot {a.slot} outside [0, {self.slots}) — a traced "
                f"out-of-range index would silently clamp into a "
                f"neighbouring slot's cache")
        # same stop-token contract as gpt.generate (rejects vocab-range
        # violations AND an explicit -1, which would alias the
        # no-eos sentinel)
        gpt._check_stop_tokens(self.cfg, a.eos_token_id, None)
        prompt = np.asarray(a.prompt, np.int32)
        if prompt.ndim != 1 or not \
                1 <= prompt.size <= self.engine_cfg.max_prompt_len:
            raise ValueError(
                f"prompt must be 1-D with "
                f"1..{self.engine_cfg.max_prompt_len} tokens, got shape "
                f"{prompt.shape}")
        room = self.engine_cfg.max_seq_len - prompt.size
        if a.max_tokens < 1 or a.max_tokens > room:
            raise ValueError(
                f"max_tokens {a.max_tokens} outside [1, {room}] for a "
                f"{prompt.size}-token prompt at max_seq_len "
                f"{self.engine_cfg.max_seq_len}")
        if a.allowed_tokens is not None:
            # pre-flight (admit_many is all-or-nothing: nothing may
            # dispatch if any row is invalid); the expansion itself is
            # owned by set_slot_mask
            self._check_allowed_tokens(a.allowed_tokens)
        if a.adapter:
            if not self._lora:
                raise ValueError(
                    f"admission carries adapter {a.adapter} but the "
                    f"adapter pool is disabled "
                    f"(EngineConfig.adapter_slots == 0)")
            if not 1 <= a.adapter < self._adapter_used:
                raise ValueError(
                    f"adapter {a.adapter} outside the registered rows "
                    f"[1, {self._adapter_used}) — register_adapter() "
                    f"first (0 is the pinned base adapter)")
            if a.prefix_page is not None:
                raise ValueError(
                    "prefix-pool hits require the base adapter (id "
                    "0): the pooled prefix was prefilled with base "
                    "weights, so an adapter-carrying hit would decode "
                    "against K/V a cold adapter prefill would not "
                    "produce")
        if a.prefix_page is not None:
            ps = a.prefix_len
            if not self._prefix_splits:
                raise ValueError(
                    "admission carries a prefix_page but the prefix "
                    "pool is disabled (EngineConfig.prefix_pool_slots "
                    "== 0)")
            if ps not in self._prefix_splits:
                raise ValueError(
                    f"prefix_len {ps} is not a usable split point "
                    f"{self._prefix_splits}")
            if not 0 <= a.prefix_page < self._prefix_used:
                raise ValueError(
                    f"prefix_page {a.prefix_page} outside the "
                    f"{self._prefix_used} registered pages")
            if prompt.size <= ps:
                raise ValueError(
                    f"prompt of {prompt.size} tokens leaves no tail "
                    f"beyond prefix_len {ps}")
            tb = self.bucket_for(prompt.size - ps)
            if (ps, tb) not in self._admit_prefix:
                raise ValueError(
                    f"no compiled extend variant for (split {ps}, "
                    f"tail bucket {tb}) — the combined block exceeds "
                    f"max_seq_len")
            stored = self._prefix_tokens[a.prefix_page]
            if tuple(int(x) for x in prompt[:ps]) != stored[:ps]:
                raise ValueError(
                    f"prompt[:{ps}] does not match the tokens "
                    f"registered on prefix page {a.prefix_page} — a "
                    f"mismatched copy would silently decode against "
                    f"another template's K/V")
        elif a.prefix_len:
            raise ValueError(
                "prefix_len without prefix_page — pass both (a "
                "match_prefix hit) or neither")
        return prompt, prompt.size

    def _check_allowed_tokens(self, allowed: Sequence[int]) -> List[int]:
        """THE constrained-decoding whitelist validation (shared by
        admission pre-flight and :meth:`set_slot_mask`)."""
        allowed = [int(t) for t in allowed]
        if not allowed or any(not 0 <= t < self.cfg.vocab_size
                              for t in allowed):
            raise ValueError(
                f"allowed token whitelist must be a non-empty subset "
                f"of vocab [0, {self.cfg.vocab_size})")
        return allowed

    def admit(self, slot: int, prompt, max_tokens: int, *,
              temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
              seed: Optional[int] = None,
              eos_token_id: Optional[int] = None) -> Tuple[int, bool, bool]:
        """Admit one request into ``slot``: prefill + first token (the
        k=1 lane of :meth:`admit_many`). Returns ``(first_token,
        hit_eos, finished)`` — ``finished`` True when the request is
        already complete after its first token (eos, or a budget of 1).
        ``max_tokens`` must fit the slot's cache horizon."""
        res = self.admit_many([Admission(
            slot=slot, prompt=prompt, max_tokens=max_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            eos_token_id=eos_token_id)])[0]
        return res.first_token, res.hit_eos, res.finished

    def admit_many(self, items: Sequence[Admission]) -> List[AdmitResult]:
        """Admit a batch of requests in as few dispatches as the ladders
        allow: ``items`` (FIFO order, distinct slots) is split into
        ``admit_batch_sizes`` groups largest-first; each group prefills
        at the smallest bucket that fits its longest prompt and runs
        ONE compiled ``(bucket, k)`` program — one forward + one cache/
        state scatter for the whole group. Per-row results are
        bit-identical to k single :meth:`admit` calls in the same
        order (the admission-parity oracle pins this)."""
        items = list(items)
        if not items:
            return []
        self._check_poisoned()
        spec = self._take_fault("admit")
        if spec is not None and spec.kind == KIND_ERROR:
            # a device error escaping the admission call: the donated
            # cache/state must be assumed consumed — poison until rebuilt
            self._poisoned = True
            raise InjectedFault(
                f"injected device error at admit: {spec.describe()}",
                point="admit", spec=spec)
        validated = [self._validate_admission(a) for a in items]
        slots_used = [a.slot for a in items]
        if len(set(slots_used)) != len(slots_used):
            raise ValueError(
                f"admit_many slots must be distinct, got {slots_used}")
        if self._paged:
            # all-or-nothing: refuse the whole batch BEFORE any
            # dispatch when the pool cannot cover it (conservative —
            # stale mappings on the target slots are not counted as
            # reclaimable; the scheduler releases slots first)
            total = sum(
                self.pages_needed(n, a.max_tokens, a.prefix_len)
                for a, (_, n) in zip(items, validated))
            if not self._page_alloc.can_alloc(total):
                raise PagesExhausted(total, self._page_alloc.free_pages)
        pending = []  # (device futures, bucket, k, group) per dispatch
        i, group = 0, 0
        while i < len(items):
            if items[i].prefix_page is not None:
                # a prefix-pool hit rides its own compiled (split,
                # tail-bucket) extend program, k=1: the copied prefix
                # replaces most of the prefill forward, so batching it
                # with cold admissions would drag it back to the full
                # bucket
                pending.append(
                    (self._dispatch_prefix_admit(items[i],
                                                 validated[i]),
                     self.bucket_for(
                         validated[i][1] - items[i].prefix_len),
                     1, group))
                i += 1
                group += 1
                continue
            run = i
            while run < len(items) and items[run].prefix_page is None:
                run += 1
            k = max(s for s in self._batch_sizes if s <= run - i)
            batch = items[i:i + k]
            proms = validated[i:i + k]
            bucket = self.bucket_for(max(n for _, n in proms))
            prompts = np.stack([self.pad_prompt(p, bucket)
                                for p, _ in proms])
            keys = np.stack([
                _threefry_key_data(a.seed) if a.seed is not None
                else np.zeros((2,), np.uint32) for a in batch])
            seeded = np.asarray([a.seed is not None for a in batch], bool)
            req_idx = np.arange(self._req_counter,
                                self._req_counter + k, dtype=np.int32)
            self._req_counter += k
            # first-token masks, and the per-slot mask rows the decode
            # steps will use (set BEFORE the dispatch that reads them;
            # unconstrained rows reset any stale mask the slot carried)
            # — one expansion, owned by set_slot_mask
            for a in batch:
                self.set_slot_mask(a.slot, a.allowed_tokens)
            masks = np.stack([self._masks[a.slot] for a in batch])
            arr = lambda vals, dt: np.asarray(vals, dt)
            fn = self._admits[(bucket, k)]
            extra: Tuple[Any, ...] = ()
            if self._paged:
                # map each row's table BEFORE the dispatch that reads
                # it; the insert writes the first ceil(bucket/P)
                # entries of each row (sink-padded past the
                # allocation)
                n_ins = -(-bucket // self.engine_cfg.page_size)
                rows = [self._alloc_slot_pages(a.slot, n, a.max_tokens)
                        for a, (_, n) in zip(batch, proms)]
                extra += (np.stack([r[:n_ins] for r in rows]),)
            if self._spec:
                extra += (np.stack([self._hist_seed(p)
                                    for p, _ in proms]),)
            if self._lora:
                # the slot's decode-path id-table entry is set BEFORE
                # the dispatch that admits it (the vocab-mask
                # contract); the admission forward reads the per-row
                # (physical — any cold row pages in here, BEFORE the
                # pool is captured into extra) ids argument
                phys = [self._adapter_physical(a.adapter)
                        for a in batch]
                for a, pr in zip(batch, phys):
                    self._set_slot_adapter(a.slot, pr)
                extra += (self.adapters,
                          np.asarray(phys, np.int32))
            self.cache, self.state, first, first_lp, hit_eos, done = fn(
                self._params, self.cache, self.state,
                arr([a.slot for a in batch], np.int32), prompts,
                arr([n for _, n in proms], np.int32),
                arr([a.max_tokens for a in batch], np.int32),
                arr([a.temperature for a in batch], np.float32),
                arr([a.top_k for a in batch], np.int32),
                arr([a.top_p for a in batch], np.float32),
                keys,
                arr([_NO_EOS if a.eos_token_id is None
                     else int(a.eos_token_id) for a in batch], np.int32),
                req_idx, seeded, masks, *extra)
            pending.append(((first, first_lp, hit_eos, done), bucket, k,
                            group))
            i += k
            group += 1
        # fetch AFTER every group is dispatched — later groups ride the
        # async queue behind earlier ones instead of waiting for each
        # fetch round trip
        results: List[AdmitResult] = []
        for (first, first_lp, hit_eos, done), bucket, k, group in pending:
            first = np.asarray(first)
            first_lp = np.asarray(first_lp)
            hit_eos, done = np.asarray(hit_eos), np.asarray(done)
            for j in range(k):
                tok = int(first[j])
                if spec is not None and spec.kind == KIND_NAN \
                        and len(results) in spec.slots:
                    tok = spec.token  # NaN prefill: garbage first token
                results.append(AdmitResult(
                    tok, bool(hit_eos[j]), bool(done[j]),
                    bucket=bucket, batch_size=k, group=group,
                    logprob=float(first_lp[j])))
        return results

    def _dispatch_prefix_admit(self, a: Admission,
                               validated: Tuple[np.ndarray, int]):
        """Dispatch ONE prefix-hit admission through its (split, tail
        bucket) extend program; returns the (first, first_lp, hit_eos,
        done) device futures (fetch deferred like every admission
        group)."""
        prompt, n = validated
        ps = a.prefix_len
        tb = self.bucket_for(n - ps)
        tails = np.full((1, tb), self.engine_cfg.pad_token_id, np.int32)
        tails[0, :n - ps] = prompt[ps:]
        keys = (_threefry_key_data(a.seed) if a.seed is not None
                else np.zeros((2,), np.uint32))[None]
        seeded = np.asarray([a.seed is not None], bool)
        req_idx = np.asarray([self._req_counter], np.int32)
        self._req_counter += 1
        self.set_slot_mask(a.slot, a.allowed_tokens)
        masks = self._masks[a.slot][None]
        fn = self._admit_prefix[(ps, tb)]
        extra: Tuple[Any, ...] = ()
        if self._paged:
            # copy-on-write mapping: shared prefix pages pinned into
            # the row, private pages allocated for the tail + decode;
            # the insert gets the row entries from the split onward
            p_szc = self.engine_cfg.page_size
            row = self._alloc_slot_pages(
                a.slot, n, a.max_tokens, prefix_page=a.prefix_page,
                prefix_len=ps)
            n_tail = -(-tb // p_szc)
            pages = np.full((n_tail,), SINK, np.int32)
            avail = row[ps // p_szc: ps // p_szc + n_tail]
            pages[:avail.size] = avail
            extra += (pages[None],)
        if self._spec:
            extra += (self._hist_seed(prompt)[None],)
        if self._lora:
            # validated adapter == 0 on the prefix path — the slot's
            # table entry resets to base and the zero row rides along
            self._set_slot_adapter(a.slot, a.adapter)
            extra += (self.adapters,
                      np.asarray([a.adapter], np.int32))
        self.cache, self.state, first, first_lp, hit_eos, done = fn(
            self._params, self.cache, self.state, self.pool,
            np.asarray([a.slot], np.int32), tails,
            np.asarray([n - ps], np.int32),
            np.asarray([a.max_tokens], np.int32),
            np.asarray([a.temperature], np.float32),
            np.asarray([a.top_k], np.int32),
            np.asarray([a.top_p], np.float32), keys,
            np.asarray([_NO_EOS if a.eos_token_id is None
                        else int(a.eos_token_id)], np.int32),
            req_idx, seeded, masks, np.int32(a.prefix_page), *extra)
        return first, first_lp, hit_eos, done

    # -- chunked prefill (EngineConfig.prefill_chunk > 0) ------------------

    @property
    def chunked_prefill_enabled(self) -> bool:
        """True when ``EngineConfig.prefill_chunk > 0``."""
        return self._chunk_size > 0

    def chunked_for(self, prompt_len: int) -> bool:
        """Whether a prompt of this length admits through chunked
        prefill (longer than one chunk) instead of :meth:`admit_many`."""
        return self._chunk_size > 0 and prompt_len > self._chunk_size

    def admit_chunked_start(self, a: Admission) -> ChunkedAdmission:
        """Begin a chunked-prefill admission: validate, map the slot's
        pages (paged mode — :class:`PagesExhausted` backpressure fires
        HERE, before any device work), and dispatch chunk 0 (the
        bucket-sized cold prefill into the compute-dtype scratch).
        Exactly one chunked admission may be in progress (the scratch
        holds one prompt); the scheduler interleaves decode waves
        between the subsequent :meth:`admit_chunked_step` calls."""
        self._check_poisoned()
        if not self._chunk_size:
            raise ValueError(
                "chunked prefill disabled "
                "(EngineConfig.prefill_chunk == 0)")
        if self._chunked is not None:
            raise RuntimeError(
                "a chunked admission is already in progress — the "
                "scratch buffer holds one prompt at a time")
        if a.prefix_page is not None:
            raise ValueError(
                "chunked prefill does not compose with prefix-pool "
                "hits (a hit already skips the prefix forward — "
                "nothing long is left to chunk)")
        prompt, n = self._validate_admission(a)
        if n <= self._chunk_size:
            raise ValueError(
                f"prompt of {n} tokens fits one {self._chunk_size}-"
                f"token chunk — use admit_many")
        if self._paged:
            self._alloc_slot_pages(a.slot, n, a.max_tokens)
        if self._lora:
            self._bind_slot_adapter(a.slot, a.adapter)
        c = self._chunk_size
        ca = ChunkedAdmission(a, prompt, n, -(-n // c))
        tok0 = prompt[:c].astype(np.int32)[None]
        lx = self._lora_args(a.adapter)
        try:
            self._chunk_scratch = self._chunk0(
                self._params, self._chunk_scratch, tok0, *lx)
        except Exception:
            # scratch donated into the failing call
            self._poisoned = True
            raise
        self._chunked = ca
        return ca

    def admit_chunked_step(self, ca: ChunkedAdmission
                           ) -> Optional[AdmitResult]:
        """Advance one chunked admission by ONE device dispatch: the
        next ``prefill_extend`` chunk while prefilling (returns None),
        then the finish — first-token draw + whole-prompt cache insert
        + slot-state scatter — returning the :class:`AdmitResult`.
        The scheduler runs decode waves between calls; that is the
        entire stall-free-admission mechanism."""
        self._check_poisoned()
        if ca is not self._chunked:
            raise ValueError(
                "stale ChunkedAdmission — not the one in progress")
        c = self._chunk_size
        a = ca.admission
        if not ca.done_prefilling:
            i = ca.next_chunk
            chunk = ca.prompt[i * c: min((i + 1) * c, ca.p_len)]
            tail = np.full((1, c), self.engine_cfg.pad_token_id,
                           np.int32)
            tail[0, :chunk.size] = chunk
            try:
                self._chunk_scratch, ca._logits = self._chunk_exts[i](
                    self._params, self._chunk_scratch, tail,
                    np.asarray([chunk.size - 1], np.int32),
                    *self._lora_args(a.adapter))
            except Exception:
                self._poisoned = True
                self._chunked = None
                raise
            ca.next_chunk += 1
            return None
        # the finish dispatch — the admission's only cache/state write
        keys = (_threefry_key_data(a.seed) if a.seed is not None
                else np.zeros((2,), np.uint32))[None]
        seeded = np.asarray([a.seed is not None], bool)
        req_idx = np.asarray([self._req_counter], np.int32)
        self._req_counter += 1
        self.set_slot_mask(a.slot, a.allowed_tokens)
        masks = self._masks[a.slot][None]
        extra: Tuple[Any, ...] = ()
        if self._paged:
            n_fin = -(-self.engine_cfg.max_prompt_len
                      // self.engine_cfg.page_size)
            extra += (self._tables[a.slot][:n_fin][None],)
        if self._spec:
            extra += (self._hist_seed(ca.prompt)[None],)
        try:
            self.cache, self.state, first, first_lp, hit_eos, done = \
                self._chunk_finish(
                    self._params, self.cache, self.state,
                    self._chunk_scratch, ca._logits,
                    np.asarray([a.slot], np.int32),
                    np.asarray([ca.p_len], np.int32),
                    np.asarray([a.max_tokens], np.int32),
                    np.asarray([a.temperature], np.float32),
                    np.asarray([a.top_k], np.int32),
                    np.asarray([a.top_p], np.float32), keys,
                    np.asarray([_NO_EOS if a.eos_token_id is None
                                else int(a.eos_token_id)], np.int32),
                    req_idx, seeded, masks, *extra)
        except Exception:
            self._poisoned = True
            self._chunked = None
            raise
        self._chunked = None
        return AdmitResult(
            int(np.asarray(first)[0]), bool(np.asarray(hit_eos)[0]),
            bool(np.asarray(done)[0]), bucket=c, batch_size=1,
            group=0, logprob=float(np.asarray(first_lp)[0]))

    def _set_slot_adapter(self, slot: int, adapter: int) -> None:
        """Point ``slot``'s decode-path adapter-id table entry at
        ``adapter`` (host mirror; the cached device copy invalidates
        only when a row actually changes — the vocab-mask upload
        discipline, so single-tenant steady state never re-uploads)."""
        if self._adapter_ids[slot] == adapter:
            return
        self._adapter_ids[slot] = adapter
        self._aids_dev = None

    def _adapter_physical(self, adapter: int) -> int:
        """Resolve a request's adapter id to its resident pool row,
        paging the row in from the host registry when cold (host_swap
        engines — ids stay DATA and the set program is pre-warmed, so
        a page-in never recompiles; identity elsewhere, where virtual
        == physical by construction). Eviction skips rows bound to a
        live slot's id-table entry — spilling one would silently swap
        weights under a decoding stream."""
        if not (self._host_swap and self._lora) or adapter == 0:
            return adapter
        phys = self._adapter_phys.get(adapter)
        if phys is not None:
            self._adapter_lru.touch(phys)
            return phys
        if self._adapter_free_rows:
            phys = self._adapter_free_rows.pop()
        else:
            pinned = {int(r) for r in self._adapter_ids if r}
            phys = self._adapter_lru.pop_coldest(pinned)
            if phys is None:
                raise ValueError(
                    f"adapter pool thrash: every resident row "
                    f"(adapter_slots={self.engine_cfg.adapter_slots}) "
                    f"is bound to a live slot — raise adapter_slots")
            stale = self._adapter_virt.pop(phys)
            self._adapter_phys.pop(stale, None)
            self._adapter_spills += 1
        # NOT donated — a failed page-in leaves every serving row
        # intact (and the maps untouched: they update after the call)
        self.adapters = self._adapter_set(
            self.adapters, self._adapter_rows_host[adapter],
            np.int32(phys))
        self._adapter_phys[adapter] = phys
        self._adapter_virt[phys] = adapter
        self._adapter_lru.touch(phys)
        self._adapter_pageins += 1
        return phys

    def _adapter_virtual(self, phys: int) -> int:
        """Inverse of :meth:`_adapter_physical` for a bound row — the
        id a park payload stores, so resume re-resolves (the physical
        row may have been spilled while parked)."""
        if not (self._host_swap and self._lora) or phys == 0:
            return phys
        return self._adapter_virt.get(phys, 0)

    def _bind_slot_adapter(self, slot: int, adapter: int) -> None:
        """Resolve-and-bind: the admission/resume seam (virtual in,
        physical in the slot's id-table entry)."""
        self._set_slot_adapter(slot, self._adapter_physical(adapter))

    def adapter_paging_stats(self) -> Optional[Dict[str, float]]:
        """Adapter-paging snapshot (None unless host_swap + adapters):
        logical registrations vs resident pool rows, spill/page-in
        traffic."""
        if not (self._host_swap and self._lora):
            return None
        return {
            "registered": float(self.adapters_registered),
            "resident": float(len(self._adapter_virt)),
            "rows": float(self.engine_cfg.adapter_slots - 1),
            "spills_total": float(self._adapter_spills),
            "pageins_total": float(self._adapter_pageins),
        }

    def _lora_args(self, adapter: int) -> Tuple[Any, ...]:
        """The trailing (pool, ids) args of a k=1 forward program
        (chunked prefill's chunk/extend dispatches) — empty when the
        pool is disabled."""
        if not self._lora:
            return ()
        aid = self._adapter_physical(adapter)
        return (self.adapters, np.asarray([aid], np.int32))

    def _hist_seed(self, prompt) -> np.ndarray:
        """The drafter-ring admission seed for one prompt: its last
        ``spec_hist - 1`` tokens, left-padded with the ``-1`` sentinel
        (the device appends the admission's first sampled token to
        complete the ring). Host-side numpy — the variable-length
        logic stays out of the compiled programs."""
        h = self.engine_cfg.spec_hist
        row = np.full((h - 1,), -1, np.int32)
        tail = np.asarray(prompt, np.int32)[-(h - 1):]
        if tail.size:
            row[h - 1 - tail.size:] = tail
        return row

    def step_async(self, *, spec: bool = False,
                   chunk: Optional[int] = None,
                   spec_k: Optional[int] = None) -> StepHandle:
        """Dispatch one decode chunk WITHOUT fetching its outputs: the
        engine rebinds its (donated) cache/state to the returned device
        futures immediately, so the caller may enqueue further work —
        the next chunk, an admission — behind it before syncing, and
        the device never idles through the host's fetch + event
        processing. Returns the chunk's :class:`StepHandle`.

        ``spec=True`` dispatches the SPECULATIVE chunk variant (a
        compiled ``spec_ks`` rung required — every variant is
        pre-warmed, so a payoff-gated or tuner-driven scheduler
        switches per dispatch without a recompile): the handle's
        tokens/logprobs/finished are ``[B, chunk * (spec_k + 1)]``
        with ``handle.valid`` marking the real emissions (rejected
        draft lanes emit pad).

        ``chunk``/``spec_k`` select among the pre-warmed step variants
        (``EngineConfig.decode_chunks`` / ``spec_ks`` — the self-tuning
        scheduler's per-dispatch knob values); ``None`` means the base
        ``decode_chunk`` / ``spec_k``. A value outside the compiled
        ladder raises instead of compiling mid-serve: dispatching an
        unwarmed variant is exactly the trace-stability breach the
        armed recompile guard exists to catch."""
        self._check_poisoned()
        c = self.engine_cfg.decode_chunk if chunk is None else int(chunk)
        if c not in self._step_variants:
            raise ValueError(
                f"decode_chunk {c} is not a pre-warmed step variant "
                f"{self._chunk_ladder} — declare it in "
                f"EngineConfig.decode_chunks (dispatching it would "
                f"compile mid-serve)")
        if spec:
            if not self._spec:
                raise ValueError(
                    "step_async(spec=True) needs a compiled spec "
                    "variant (EngineConfig.spec_k > 0 or spec_ks)")
            k = (self.engine_cfg.spec_k if spec_k is None
                 else int(spec_k))
            if (c, k) not in self._spec_variants:
                raise ValueError(
                    f"spec_k {k} (at decode_chunk {c}) is not a "
                    f"pre-warmed spec variant — declare it in "
                    f"EngineConfig.spec_ks {self._spec_ladder}")
        elif spec_k not in (None, 0):
            raise ValueError(
                f"spec_k={spec_k} without spec=True — a plain chunk "
                f"has no draft width")
        fspec = self._take_fault("dispatch")
        if fspec is not None and fspec.kind == KIND_ERROR:
            self._poisoned = True
            raise InjectedFault(
                f"injected device error at dispatch: "
                f"{fspec.describe()}", point="dispatch", spec=fspec)
        if self._masks_dev is None:
            self._masks_dev = jnp.asarray(self._masks)
        step_extra: Tuple[Any, ...] = ()
        if self._paged:
            # the block tables ride every dispatch as DATA (one static
            # [B, max_pages] int32 argument — same contract as the
            # masks; the device copy is cached until a row changes)
            if self._tables_dev is None:
                self._tables_dev = jnp.asarray(self._tables)
            step_extra = (self._tables_dev,)
        if self._lora:
            # the adapter pool + per-slot id table ride every dispatch
            # as DATA (ids cached like the masks/tables; the pool is
            # the engine-owned device buffer registrations update)
            if self._aids_dev is None:
                self._aids_dev = jnp.asarray(self._adapter_ids)
            step_extra += (self.adapters, self._aids_dev)
        valid = None
        if spec:
            (self.cache, self.state, emit, logprobs, finished,
             valid) = self._spec_variants[(c, k)](
                self._params, self.cache, self.state, self._masks_dev,
                *step_extra)
            spec_k, ncols = k, c * (k + 1)
        else:
            self.cache, self.state, emit, logprobs, finished = \
                self._step_variants[c](
                    self._params, self.cache, self.state,
                    self._masks_dev, *step_extra)
            spec_k, ncols = 0, c
        plan = None if self._warming else self.fault_plan
        return StepHandle(emit, logprobs, finished, plan=plan,
                          hang=fspec if fspec is not None
                          and fspec.kind == KIND_HANG else None,
                          on_poison=self._mark_poisoned,
                          valid=valid, spec_k=spec_k, ncols=ncols)

    def step(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One decode chunk over every slot — ``decode_chunk`` fused
        per-token steps in one dispatch, fetched synchronously
        (:meth:`step_async` + :meth:`StepHandle.fetch`). Returns
        ``(tokens [B, n], logprobs [B, n], finished [B, n])`` with
        ``n = decode_chunk``; column ``j`` holds step ``j``'s emissions,
        ``pad_token_id`` for slots that were done entering that step (a
        slot that finishes at column ``j`` emits pad from ``j + 1``
        on)."""
        return self.step_async().fetch()

    def set_slot_mask(self, slot: int,
                      allowed: Optional[Sequence[int]] = None) -> None:
        """Replace ``slot``'s constrained-decoding vocab mask with the
        whitelist ``allowed`` (``None`` = unconstrained, all-True). The
        schema DFA advances host-side per emitted token; the scheduler
        calls this between chunk dispatches, so the next compiled step
        reads the advanced mask — no recompile (the mask is data, one
        static ``[B, vocab]`` bool argument of the step program)."""
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} outside [0, {self.slots})")
        if allowed is None:
            # the hot unconstrained case (every admission resets its
            # slot's row): an already-all-True row must NOT invalidate
            # the cached device copy — that would re-upload the whole
            # [B, vocab] array after every admission wave
            if self._masks[slot].all():
                return
            self._masks[slot, :] = True
        else:
            allowed = self._check_allowed_tokens(allowed)
            row = np.zeros((self.cfg.vocab_size,), bool)
            row[allowed] = True
            if (self._masks[slot] == row).all():
                return  # unchanged (e.g. a DFA state with the same set)
            self._masks[slot] = row
        self._masks_dev = None

    def retire(self, slot: int) -> None:
        """Force ``slot`` done (scheduler deadline expiry). The slot's
        lane keeps riding the compiled step unmodified; its output is
        pad until the next admission overwrites the state. Takes effect
        for chunks dispatched AFTER this call — chunks already in
        flight still carry the slot's real tokens (a pipelined
        scheduler drops them)."""
        self._check_poisoned()
        spec = self._take_fault("retire")
        if spec is not None and spec.kind == KIND_ERROR:
            self._poisoned = True
            raise InjectedFault(
                f"injected device error at retire: {spec.describe()}",
                point="retire", spec=spec)
        self.state = self._retire(self.state, np.int32(slot))

    # -- failure isolation (apex_tpu.serving.resilience) -------------------

    def _take_fault(self, point: str):
        plan = self.fault_plan
        if plan is None or self._warming:
            return None
        return plan.take(point)

    def _mark_poisoned(self) -> None:
        self._poisoned = True

    def _check_poisoned(self) -> None:
        if self._poisoned:
            raise EngineFault(
                "engine state is poisoned (a prior fault invalidated "
                "the donated cache/state buffers); call rebuild_slots() "
                "before the next device call")

    @property
    def poisoned(self) -> bool:
        """True after a fault invalidated the donated cache/state
        buffers (every device call raises until
        :meth:`rebuild_slots`)."""
        return self._poisoned

    def rebuild_slots(self) -> None:
        """Recovery: rebuild the donated cache/state buffers from the
        compiled ``init`` program (every slot comes back FREE — the
        scheduler deterministically replays interrupted requests from
        its host-side slot snapshot, see
        :mod:`apex_tpu.serving.resilience`). No recompilation: ``init``
        was compiled at construction, so a recompile guard stays armed
        through recovery. The shared-prefix pool is untouched — it is
        never donated to a failing step/admit call, so registered
        templates survive recovery and replayed prefix hits reuse
        them."""
        if self._paged:
            # slot mappings die with the slots; registered prefixes
            # keep their registration pin (the pool block survives,
            # and the quantized page-in is replayed below into the
            # fresh cache)
            for slot in list(self._slot_pages):
                self._free_slot_pages(slot)
            self._tables[:, :] = SINK
            self._tables_dev = None
        self._chunked = None
        self.cache, self.state = self._init(self._params)
        if self._chunk_size:
            self._chunk_scratch = self._chunk_scratch_init(self._params)
        if self._paged and self._prefix_pages:
            for page in sorted(self._prefix_pages):
                pb = len(self._prefix_tokens[page])
                self.cache = self._pool_pageins[pb](
                    self.cache, self.pool, np.int32(page),
                    np.asarray([self._prefix_pages[page]], np.int32))
        self._masks[:, :] = True
        self._masks_dev = None
        if self._lora:
            # the adapter POOL survives (never donated — registered
            # tenants keep serving); only the per-slot id table resets
            # with the slots it describes
            self._adapter_ids[:] = 0
            self._aids_dev = None
        self._poisoned = False

    def warmup(self) -> "Engine":
        """Compile every engine program up front — ``init``, ``step``,
        ``retire``, and ALL ``(bucket, k)`` admission variants — then
        reset the slot state, so :meth:`recompile_guard` can be armed
        immediately after and stay flat across any serve cycle (the
        host admission path is jax-free — seeded keys are packed with
        numpy — so nothing else can compile mid-serve). Call BEFORE
        admitting real requests (the reset frees every slot);
        idempotent. Replaces the hand-rolled one-admit-one-step
        warmups tests and examples used to do."""
        if self._warmed:
            return self
        self._warming = True  # warmup must not consume fault-plan seams
        try:
            with expected_compiles():
                # warmup IS the sanctioned compile pass: its events
                # must never be attributed to another live engine's
                # armed guard (the fleet router warms replacement
                # replicas mid-serve)
                self._warmup_body()
        finally:
            self._warming = False
        self._warmed = True
        return self

    def _warmup_body(self) -> None:
        ecfg = self.engine_cfg
        hseed = lambda k: (
            (np.full((k, ecfg.spec_hist - 1), -1, np.int32),)
            if self._spec else ())
        # paged warm args: sink-page indices — every warmup insert
        # lands in the garbage page, so no allocator state is touched
        wpages = lambda k, span: (
            (np.full((k, -(-span // ecfg.page_size)), SINK, np.int32),)
            if self._paged else ())
        # lora warm args: every row rides the pinned zero adapter —
        # shapes are what compile, and id 0 is the base row anyway
        wlora = lambda k: ((self.adapters, np.zeros((k,), np.int32))
                           if self._lora else ())
        for (bucket, k), fn in sorted(self._admits.items()):
            # dummy args exercise shapes only: k pad-token prompts of
            # length 1, budget 1 (done at admission), no sampling
            self.cache, self.state, first, _, _, _ = fn(
                self._params, self.cache, self.state,
                np.arange(k, dtype=np.int32),
                np.full((k, bucket), ecfg.pad_token_id, np.int32),
                np.ones((k,), np.int32), np.ones((k,), np.int32),
                np.zeros((k,), np.float32), np.zeros((k,), np.int32),
                np.ones((k,), np.float32),
                np.zeros((k, 2), np.uint32),
                np.full((k,), _NO_EOS, np.int32),
                np.zeros((k,), np.int32), np.zeros((k,), bool),
                np.ones((k, self.cfg.vocab_size), bool),
                *wpages(k, bucket), *hseed(k), *wlora(k))
            np.asarray(first)
        if self._lora:
            # compile the registration write against a zero row — row
            # 0 is the pinned zero adapter, so the warm write is a
            # no-op on pool CONTENT and register_adapter() later never
            # trips an armed recompile guard. Shapes come from THE
            # shape table registration validates against, so the two
            # can never compile different programs.
            zero_row = {
                site: {part: np.zeros(shape, np.float32)
                       for part, shape in parts.items()}
                for site, parts in self._lora_expected_shapes().items()}
            self.adapters = self._adapter_set(self.adapters, zero_row,
                                              np.int32(0))
        if self._chunk_size:
            # the chunked-prefill ladder: chunk 0, every extend
            # variant, then the finish — junk tokens, logits flow
            # through so the finish compiles against the real dtypes
            c = self._chunk_size
            self._chunk_scratch = self._chunk0(
                self._params, self._chunk_scratch,
                np.full((1, c), ecfg.pad_token_id, np.int32),
                *wlora(1))
            lg = None
            for i, fn in sorted(self._chunk_exts.items()):
                self._chunk_scratch, lg = fn(
                    self._params, self._chunk_scratch,
                    np.full((1, c), ecfg.pad_token_id, np.int32),
                    np.zeros((1,), np.int32), *wlora(1))
            self.cache, self.state, first, _, _, _ = self._chunk_finish(
                self._params, self.cache, self.state,
                self._chunk_scratch, lg,
                np.zeros((1,), np.int32),
                np.full((1,), 2, np.int32), np.ones((1,), np.int32),
                np.zeros((1,), np.float32), np.zeros((1,), np.int32),
                np.ones((1,), np.float32), np.zeros((1, 2), np.uint32),
                np.full((1,), _NO_EOS, np.int32),
                np.zeros((1,), np.int32), np.zeros((1,), bool),
                np.ones((1, self.cfg.vocab_size), bool),
                *wpages(1, ecfg.max_prompt_len), *hseed(1))
            np.asarray(first)
        # prefix pool: compile every pool-insert and (split, tail
        # bucket) extend variant against page 0 junk
        if self._prefix_used:
            raise ValueError(
                "register_prefix() was called before warmup(): warmup "
                "resets the pool to shed its compile-time junk, which "
                "would silently drop the registered templates — call "
                "warmup() first, then register")
        for pb, fn in sorted(self._pool_inserts.items()):
            self.pool = fn(self._params, self.pool,
                           np.full((1, pb), ecfg.pad_token_id,
                                   np.int32), np.int32(0))
        for pb, fn in sorted(self._pool_pageins.items()):
            self.cache = fn(self.cache, self.pool, np.int32(0),
                            *wpages(1, pb))
        for (ps, tb), fn in sorted(self._admit_prefix.items()):
            self.cache, self.state, first, _, _, _ = fn(
                self._params, self.cache, self.state, self.pool,
                np.zeros((1,), np.int32),
                np.full((1, tb), ecfg.pad_token_id, np.int32),
                np.ones((1,), np.int32), np.ones((1,), np.int32),
                np.zeros((1,), np.float32), np.zeros((1,), np.int32),
                np.ones((1,), np.float32), np.zeros((1, 2), np.uint32),
                np.full((1,), _NO_EOS, np.int32),
                np.zeros((1,), np.int32), np.zeros((1,), bool),
                np.ones((1, self.cfg.vocab_size), bool), np.int32(0),
                *wpages(1, tb), *hseed(1), *wlora(1))
            np.asarray(first)
        # every step variant compiles here — each decode-chunk rung
        # and each (chunk, spec_k) cross — so the scheduler's payoff
        # gate AND the self-tuning controller can flip variants per
        # dispatch under an armed recompile guard (the serving.tuner
        # pre-warm contract; WARMUP-COVERAGE pins this loop statically)
        for c in sorted(self._step_variants):
            self.step_async(chunk=c).fetch()
        for (c, k) in sorted(self._spec_variants):
            self.step_async(spec=True, chunk=c, spec_k=k).fetch()
        if self._host_swap:
            # the swap tier: gather sink junk out at every rung and
            # scatter it straight back into the sink page — allocator
            # untouched, shapes/dtypes exactly what park/resume pass
            # (host-fetched blocks and state rows), so the armed guard
            # stays flat across swap churn
            srow = {k: np.asarray(self.state[k])[:1]
                    for k in self.state}
            self.state = self._state_restore(self.state, srow,
                                             np.int32(0))
            for n in self._swap_rungs:
                pages = np.full((n,), SINK, np.int32)
                block = jax.tree.map(np.asarray,
                                     self._swap_outs[n](self.cache,
                                                        pages))
                self.cache = self._swap_ins[n](self.cache, block,
                                               pages)
        self.state = self._retire(self.state, np.int32(0))
        # drop the warmup junk: a fresh init (compiled at construction)
        # frees every slot again
        self.cache, self.state = self._init(self._params)
        if self._chunk_size:
            self._chunk_scratch = self._chunk_scratch_init(self._params)
            self._chunked = None
        if self._paged:
            # warmup only ever wrote sink pages, but reset the host
            # mappings anyway so registration starts from a clean pool
            self._page_alloc.reset()
            self._tables[:, :] = SINK
            self._tables_dev = None
            self._slot_pages.clear()
            self._prefix_pages.clear()
        if self._prefix_splits:
            # warmup wrote junk into pool page 0 — reset the pool AND
            # the host registry, so templates register on clean pages
            # (register AFTER warmup; the insert programs are compiled
            # now, so registration never trips a recompile guard)
            self.pool = self._pool_init(self._params)
            self._prefix_index.clear()
            self._prefix_tokens.clear()
            self._prefix_used = 0
        if self._lora:
            # symmetric reset: warmup only ever wrote zeros into the
            # (all-zero) pool, but a fresh init keeps the adapter
            # lifecycle identical to the prefix pool's — warmup, then
            # register on a clean pool, both programs already compiled
            self.adapters = self._adapter_init(self._params)
            self._adapter_names.clear()
            self._adapter_meta.clear()
            self._adapter_used = 1
            self._adapter_ids[:] = 0
            self._aids_dev = None
            if self._host_swap:
                self._adapter_rows_host.clear()
                self._adapter_phys.clear()
                self._adapter_virt.clear()
                self._adapter_lru = hostswap.LRUIndex()
                self._adapter_free_rows = list(
                    range(self.engine_cfg.adapter_slots - 1, 0, -1))

    def _admit_variant_name(self, bucket: int, k: int) -> str:
        return f"admit_p{bucket}_k{k}"

    def _prefix_program_items(self):
        """(name, compiled fn) for every prefix-pool program — shared
        by :meth:`compiled_cache_sizes` and the recompile sentinel so
        the two can never disagree on what is tracked."""
        items = []
        if self._prefix_splits:
            items.append(("pool_init", self._pool_init))
            for pb, fn in sorted(self._pool_inserts.items()):
                items.append((f"pool_p{pb}", fn))
            for pb, fn in sorted(self._pool_pageins.items()):
                items.append((f"pool_pagein_p{pb}", fn))
            for (ps, tb), fn in sorted(self._admit_prefix.items()):
                items.append((f"admit_prefix_p{ps}_t{tb}", fn))
        return items

    def _lora_program_items(self):
        """(name, compiled fn) for the multi-LoRA programs — shared by
        :meth:`compiled_cache_sizes` and the recompile sentinel, same
        contract as :meth:`_prefix_program_items`. (``adapter_init``
        runs at construction, ``adapter_set`` at warmup + every
        registration — both must stay at one cache entry.)"""
        items = []
        if self._lora:
            items.append(("adapter_init", self._adapter_init))
            items.append(("adapter_set", self._adapter_set))
        return items

    def _swap_program_items(self):
        """(name, compiled fn) for every host-swap program — shared by
        :meth:`compiled_cache_sizes` and the recompile sentinel, same
        contract as :meth:`_prefix_program_items`: one gather + one
        scatter per swap-batch rung, plus the state-row restore."""
        items = []
        if self._host_swap:
            for n, fn in sorted(self._swap_outs.items()):
                items.append((f"swap_out_n{n}", fn))
            for n, fn in sorted(self._swap_ins.items()):
                items.append((f"swap_in_n{n}", fn))
            items.append(("state_restore", self._state_restore))
        return items

    def _chunk_program_items(self):
        """(name, compiled fn) for every chunked-prefill program —
        shared by :meth:`compiled_cache_sizes` and the recompile
        sentinel, same contract as :meth:`_prefix_program_items`."""
        items = []
        if self._chunk_size:
            items.append(("chunk_scratch_init",
                          self._chunk_scratch_init))
            items.append(("chunk0", self._chunk0))
            for i, fn in sorted(self._chunk_exts.items()):
                items.append((f"chunk_ext_{i}", fn))
            items.append(("chunk_finish", self._chunk_finish))
        return items

    def compiled_cache_sizes(self) -> Dict[str, Any]:
        """jit-cache entry count per program — the trace-stability
        probe: after warmup each must stay at 1 no matter how many
        requests were admitted (the oracle test asserts this). The
        aggregate ``"admit"`` key is the MAX over the per-(bucket, k)
        variants (each also reported under ``admit_p{bucket}_k{k}``;
        prefix-pool extend variants ``admit_prefix_p{split}_t{tail}``
        count too — they ARE admissions), so it reads exactly like the
        single-program days: 1 = stable."""
        size_of = lambda fn: (fn._cache_size()
                              if callable(getattr(fn, "_cache_size", None))
                              else None)
        out = {name: size_of(getattr(self, f"_{name}"))
               for name in ("init", "retire")}
        # step variants: one entry per rung (`step_c{chunk}` /
        # `step_spec_c{chunk}_k{k}`) plus the aggregate MAX under the
        # historical names, exactly the "admit" convention below — the
        # tuner switches among these, so each must stay at 1
        step_sizes, spec_sizes = [], []
        for c, fn in sorted(self._step_variants.items()):
            s = size_of(fn)
            out[f"step_c{c}"] = s
            if s is not None:
                step_sizes.append(s)
        out["step"] = max(step_sizes) if step_sizes else None
        for (c, k), fn in sorted(self._spec_variants.items()):
            s = size_of(fn)
            out[f"step_spec_c{c}_k{k}"] = s
            if s is not None:
                spec_sizes.append(s)
        if self._spec:
            out["step_spec"] = max(spec_sizes) if spec_sizes else None
        admit_sizes = []
        for (bucket, k), fn in sorted(self._admits.items()):
            s = size_of(fn)
            out[self._admit_variant_name(bucket, k)] = s
            if s is not None:
                admit_sizes.append(s)
        for name, fn in (self._prefix_program_items()
                         + self._chunk_program_items()
                         + self._lora_program_items()
                         + self._swap_program_items()):
            s = size_of(fn)
            out[name] = s
            if s is not None and name.startswith("admit_prefix"):
                admit_sizes.append(s)
        out["admit"] = max(admit_sizes) if admit_sizes else None
        return out

    # -- recompile sentinel (apex_tpu.telemetry.recompile) -----------------

    def recompile_sentinel(self, registry=None):
        """The engine's installed
        :class:`apex_tpu.telemetry.recompile.RecompileSentinel`, created
        on first call with every compiled program tracked —
        init/step/retire plus one ``admit_p{bucket}_k{k}`` entry per
        admission variant (so ``compiles_total()["tracked"]``
        attributes growth by name). Pass ``registry`` on the first
        call to mirror compile/alarm counters into ``/metrics`` —
        passing it once a registry-less sentinel exists raises rather
        than silently dropping the wiring (the counters would simply
        never appear in scrapes)."""
        if self._sentinel is not None and registry is not None \
                and registry is not self._sentinel.registry:
            raise ValueError(
                "this engine's recompile sentinel already exists (an "
                "earlier recompile_sentinel()/recompile_guard() call) "
                "and cannot adopt a different registry retroactively; "
                "pass registry on the FIRST call, or engine.close() to "
                "discard the old sentinel")
        if self._sentinel is None:
            from apex_tpu.telemetry.recompile import RecompileSentinel

            sentinel = RecompileSentinel(registry=registry).install()
            for name in ("init", "retire"):
                sentinel.track(name, getattr(self, f"_{name}"))
            for c, fn in sorted(self._step_variants.items()):
                sentinel.track(f"step_c{c}", fn)
            for (c, k), fn in sorted(self._spec_variants.items()):
                sentinel.track(f"step_spec_c{c}_k{k}", fn)
            for (bucket, k), fn in sorted(self._admits.items()):
                sentinel.track(self._admit_variant_name(bucket, k), fn)
            for name, fn in (self._prefix_program_items()
                             + self._chunk_program_items()
                             + self._lora_program_items()
                             + self._swap_program_items()):
                sentinel.track(name, fn)
            self._sentinel = sentinel
        return self._sentinel

    def recompile_guard(self, *, raise_on_recompile: bool = True,
                        registry=None):
        """Arm the never-recompile-after-warmup invariant: enter the
        returned context once every program has compiled
        (:meth:`warmup` covers all of them) and any later compilation —
        process-wide event or growth of this engine's program caches —
        increments the alarm counter and (by default) raises
        :class:`~apex_tpu.telemetry.recompile.RecompileError`::

            engine.warmup()
            with engine.recompile_guard():
                serve_forever()
        """
        return self.recompile_sentinel(registry=registry).guard(
            raise_on_recompile=raise_on_recompile)

    def close(self) -> None:
        """Release process-wide telemetry hooks — the recompile
        sentinel's ``jax.monitoring`` listener stays registered for
        process lifetime otherwise, so engines created in a loop (the
        bench's chunk sweep, a service rebuilding on config reload)
        must close the old one. Idempotent AND re-entrant: the sentinel
        reference is detached BEFORE the listener is released, so a
        second ``close()`` — or one racing a bundle-triggered dump that
        reads the sentinel — can never double-release (a double
        unregister-by-callback could detach a listener a NEWER sentinel
        just registered). The engine itself remains usable, and a later
        :meth:`recompile_sentinel` call reinstalls a fresh sentinel."""
        sentinel, self._sentinel = self._sentinel, None
        if sentinel is not None:
            sentinel.uninstall()

    def __enter__(self) -> "Engine":
        """Context-manager form: ``with Engine(...) as eng:`` closes on
        exit — the ergonomic fix for the "engines created in a loop
        must call close()" footgun (a leaked sentinel listener outlives
        the engine otherwise)."""
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
