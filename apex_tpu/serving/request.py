"""Host-side request/response types for the serving engine.

The device side of the engine only sees fixed-shape vectors; everything
request-scoped and dynamically sized — prompt tokens, deadlines, the
response token stream — lives in these plain dataclasses. Finish
reasons mirror the three ways a slot is released: the request emitted
its stop token (``eos``), exhausted its token budget (``length``), or
blew its deadline and was retired by the scheduler (``timeout``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

FINISH_EOS = "eos"
#: a host-side finish: a stop sequence matched on the streamed tail
#: (the matched tokens are trimmed from the stream), or the request's
#: schema constraint reached its final state (the emitted text is a
#: complete schema-valid value)
FINISH_STOP = "stop"
FINISH_LENGTH = "length"
FINISH_TIMEOUT = "timeout"
#: the request was interrupted by a fault and its bounded retries were
#: exhausted (or the engine failed terminally) — the resilience layer's
#: outcome, see :mod:`apex_tpu.serving.resilience`
FINISH_ERROR = "error"

#: every finish reason, in release-path order — label values for the
#: scheduler's ``serving_requests_finished_total`` counter (pre-created
#: per reason so a scrape shows explicit zeros, not absent series)
FINISH_REASONS = (FINISH_EOS, FINISH_STOP, FINISH_LENGTH, FINISH_TIMEOUT,
                  FINISH_ERROR)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls — the scalar arguments of
    ``gpt.generate``, carried as data so every request in the batch can
    differ. ``temperature == 0`` is greedy argmax (``seed`` unused);
    ``top_k``/``top_p`` use the same disabled sentinels (0 / 1.0) and
    warper order as :func:`apex_tpu.serving.sampling.draw`."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    def validate(self) -> None:
        if self.temperature > 0.0 and self.seed is None:
            raise ValueError("temperature > 0 needs a seed")
        if (self.top_k > 0 or self.top_p < 1.0) and self.temperature <= 0.0:
            raise ValueError("top_k/top_p filter sampled draws; set "
                             "temperature > 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


@dataclasses.dataclass
class Request:
    """One generation request. ``deadline`` is an absolute scheduler-clock
    time (``time.monotonic`` unless the scheduler was given another
    clock); ``None`` never times out.

    ``stop`` is a list of stop TOKEN sequences, matched host-side on
    the streamed tail: when one matches, the request finishes with
    reason :data:`FINISH_STOP` and the matched tokens are trimmed from
    the stream — tokens that could still be a stop prefix are held
    back, so a client never sees part of a stop sequence (the
    byte-level API front end compiles stop STRINGS down to these).

    ``tenant`` is the request's tenant identity (the API layer fills
    it from the ``X-Tenant-Id`` header or the OpenAI ``user`` field;
    ``"default"`` otherwise) — the scheduler's weighted-fair queueing,
    rate limits, and per-tenant accounting key
    (:mod:`apex_tpu.serving.tenancy`). ``adapter`` selects the
    request's LoRA adapter row in the engine's static pool (0 = the
    pinned base model; ids come from ``Engine.register_adapter``), so
    many fine-tunes share one compiled engine batch.

    ``constraint`` is an optional schema-constrained-decoding DFA (see
    :mod:`apex_tpu.serving.api.constrain` for the JSON implementation)
    the scheduler drives opaquely; it must expose ``reset()`` (called
    at every (re-)admission, so fault replay restarts it),
    ``allowed_tokens() -> Sequence[int]`` (the current vocab
    whitelist, uploaded as the slot's mask), ``advance(token)`` (fold
    one emitted token), and ``done`` (True = the value is complete; the
    scheduler finishes the request with :data:`FINISH_STOP`).
    Constrained requests require ``decode_chunk == 1`` — the mask
    advances between dispatches."""

    request_id: str
    prompt: Sequence[int]
    max_tokens: int
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    deadline: Optional[float] = None
    arrival_time: Optional[float] = None  # stamped by Scheduler.submit
    stop: Optional[Sequence[Sequence[int]]] = None
    constraint: Optional[Any] = None
    tenant: str = "default"
    adapter: int = 0


@dataclasses.dataclass
class StreamEvent:
    """One element of the response stream: a token (or, for a request
    finishing with zero tokens, just the finish flag) for ``request_id``.
    ``error`` carries fault context when the resilience layer
    interrupts the request — with ``finished=False`` it announces a
    retry in progress (the stream will resume), with
    ``finished=True`` and ``finish_reason="error"`` the request is
    over."""

    request_id: str
    token: Optional[int]
    finished: bool
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    #: the model's log-probability of ``token`` (log-softmax of the raw
    #: logits) — None on token-less events
    logprob: Optional[float] = None


@dataclasses.dataclass
class Completion:
    """Terminal state of a request. ``ttft`` is arrival → first token on
    the host; ``latency`` is arrival → completion (both in scheduler-clock
    seconds, ``None`` for zero-token completions' ttft). ``logprobs``
    aligns 1:1 with ``tokens`` (the model's log-probability of each)."""

    request_id: str
    tokens: List[int]
    finish_reason: str
    ttft: Optional[float] = None
    latency: Optional[float] = None
    logprobs: Optional[List[float]] = None


class StopMatcher:
    """Streaming stop-sequence matcher with trimmed emission.

    Feed each generated ``(token, logprob)`` through :meth:`push`; it
    returns the pairs now safe to stream and whether a stop sequence
    just completed. The matcher holds back exactly the longest tail of
    the stream that is a proper prefix of some stop sequence, so a
    client never sees tokens that turn out to belong to a stop — and
    on a match the stop's tokens are dropped (trimmed), never flushed.
    Deterministic in the token stream, so fault replay re-derives the
    identical flush pattern (the scheduler's suppression counts stay
    aligned)."""

    __slots__ = ("stops", "pending", "matched")

    def __init__(self, stops: Sequence[Sequence[int]]):
        self.stops: List[Tuple[int, ...]] = [
            tuple(int(t) for t in s) for s in stops if len(s)]
        self.pending: List[Tuple[int, float]] = []
        self.matched = False

    def push(self, token: int, logprob: float = 0.0
             ) -> Tuple[List[Tuple[int, float]], bool]:
        """Fold one generated token; returns ``(flushed_pairs,
        matched)``. After a match the matcher is terminal (``matched``
        stays True; the scheduler releases the request)."""
        if not self.stops:
            return [(token, logprob)], False
        self.pending.append((token, logprob))
        toks = tuple(t for t, _ in self.pending)
        for s in self.stops:
            if len(toks) >= len(s) and toks[-len(s):] == s:
                flushed = self.pending[:len(self.pending) - len(s)]
                self.pending = []
                self.matched = True
                return flushed, True
        # hold back the longest suffix that is a proper prefix of some
        # stop — by induction that suffix always lies inside pending
        keep = 0
        for j in range(1, len(toks) + 1):
            suf = toks[-j:]
            if any(len(s) > j and s[:j] == suf for s in self.stops):
                keep = j
        cut = len(self.pending) - keep
        flushed, self.pending = self.pending[:cut], self.pending[cut:]
        return flushed, False

    def flush(self) -> List[Tuple[int, float]]:
        """Release every held pair (a non-stop finish — eos, length,
        deadline, error — streams the held tail instead of trimming
        it)."""
        out, self.pending = self.pending, []
        return out
