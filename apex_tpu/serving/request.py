"""Host-side request/response types for the serving engine.

The device side of the engine only sees fixed-shape vectors; everything
request-scoped and dynamically sized — prompt tokens, deadlines, the
response token stream — lives in these plain dataclasses. Finish
reasons mirror the three ways a slot is released: the request emitted
its stop token (``eos``), exhausted its token budget (``length``), or
blew its deadline and was retired by the scheduler (``timeout``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_TIMEOUT = "timeout"
#: the request was interrupted by a fault and its bounded retries were
#: exhausted (or the engine failed terminally) — the resilience layer's
#: outcome, see :mod:`apex_tpu.serving.resilience`
FINISH_ERROR = "error"

#: every finish reason, in release-path order — label values for the
#: scheduler's ``serving_requests_finished_total`` counter (pre-created
#: per reason so a scrape shows explicit zeros, not absent series)
FINISH_REASONS = (FINISH_EOS, FINISH_LENGTH, FINISH_TIMEOUT, FINISH_ERROR)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls — the scalar arguments of
    ``gpt.generate``, carried as data so every request in the batch can
    differ. ``temperature == 0`` is greedy argmax (``seed`` unused);
    ``top_k``/``top_p`` use the same disabled sentinels (0 / 1.0) and
    warper order as :func:`apex_tpu.serving.sampling.draw`."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    def validate(self) -> None:
        if self.temperature > 0.0 and self.seed is None:
            raise ValueError("temperature > 0 needs a seed")
        if (self.top_k > 0 or self.top_p < 1.0) and self.temperature <= 0.0:
            raise ValueError("top_k/top_p filter sampled draws; set "
                             "temperature > 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


@dataclasses.dataclass
class Request:
    """One generation request. ``deadline`` is an absolute scheduler-clock
    time (``time.monotonic`` unless the scheduler was given another
    clock); ``None`` never times out."""

    request_id: str
    prompt: Sequence[int]
    max_tokens: int
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    deadline: Optional[float] = None
    arrival_time: Optional[float] = None  # stamped by Scheduler.submit


@dataclasses.dataclass
class StreamEvent:
    """One element of the response stream: a token (or, for a request
    finishing with zero tokens, just the finish flag) for ``request_id``.
    ``error`` carries fault context when the resilience layer
    interrupts the request — with ``finished=False`` it announces a
    retry in progress (the stream will resume), with
    ``finished=True`` and ``finish_reason="error"`` the request is
    over."""

    request_id: str
    token: Optional[int]
    finished: bool
    finish_reason: Optional[str] = None
    error: Optional[str] = None


@dataclasses.dataclass
class Completion:
    """Terminal state of a request. ``ttft`` is arrival → first token on
    the host; ``latency`` is arrival → completion (both in scheduler-clock
    seconds, ``None`` for zero-token completions' ttft)."""

    request_id: str
    tokens: List[int]
    finish_reason: str
    ttft: Optional[float] = None
    latency: Optional[float] = None
