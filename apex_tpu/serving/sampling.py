"""Token sampling — the ONE temperature/top-k/top-p implementation.

Extracted from ``gpt.generate`` so the batch-of-one decode path and the
continuous-batching engine can never diverge: :func:`draw` is the
scalar-parameter form ``generate``/the examples use, and
:func:`draw_slots` is the per-slot vectorised form the serving engine
threads through its compiled step — each slot's token is bit-identical
to what a solo ``generate`` call with that slot's parameters would draw
(the engine's continuous-batching oracle pins this token-for-token).

Filters compose in the mainstream (HF/Megatron warper) order — the
caller applies temperature first, then top-k, then nucleus mass measured
on the renormalized top-k distribution — with static shapes throughout
(the form ``lax.scan`` and jit need). :func:`filter_logits` takes
Python-int/float parameters (free when disabled); the traced variant
inside :func:`draw_slots` takes them as device scalars so per-request
values never trigger a recompile, and is value-equal to the static form
for enabled and disabled settings alike.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def filter_logits(logits, top_k: int, top_p: float, mask=None):
    """Nucleus/top-k logit filtering: positions outside the top-k (by
    value), or outside the smallest set whose softmax mass reaches
    top_p, are masked to -inf. ``top_k``/``top_p`` are static Python
    values; 0 / outside (0, 1) disable. One sort; static shapes.

    ``mask`` (optional, bool ``[..., vocab]``) is the constrained-
    decoding vocab mask: False positions are removed from the candidate
    set BEFORE the top-k/top-p filters, so the filters act on the
    allowed distribution (an all-True mask is value-identical to no
    mask). The serving engine threads a per-slot mask through the
    traced variant; the host-side schema DFA
    (:mod:`apex_tpu.serving.api.constrain`) advances it per emitted
    token."""
    vocab = logits.shape[-1]
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    kk = top_k if 0 < top_k < vocab else 0
    pp = top_p if 0.0 < top_p < 1.0 else 0.0
    if not kk and not pp:
        return logits
    neg = jnp.finfo(logits.dtype).min
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    if kk:
        # masking the sorted tail IS the top-k filter (no second sort)
        sorted_desc = jnp.where(
            jnp.arange(vocab) < kk, sorted_desc, neg)
        thresh = sorted_desc[..., kk - 1][..., None]
    else:
        thresh = None
    if pp:
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep every position whose *preceding* cumulative mass is below
        # top_p (the first token is always kept)
        keep = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < pp],
            axis=-1)
        # threshold value = smallest kept logit
        pthresh = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True)
        thresh = pthresh if thresh is None else jnp.maximum(thresh, pthresh)
    return jnp.where(logits < thresh, neg, logits)


def _filter_logits_traced(logits, top_k, top_p):
    """:func:`filter_logits` with *traced* scalar parameters (per-slot
    values under vmap). Value-identical to the static form: disabled
    settings map to sentinels that keep every position — ``top_k`` off →
    k = vocab (the k-threshold becomes the minimum logit, which masks
    nothing), ``top_p`` off → mass bound +inf (every position kept, the
    p-threshold likewise the minimum)."""
    vocab = logits.shape[-1]
    neg = jnp.finfo(logits.dtype).min
    kk = jnp.where((top_k > 0) & (top_k < vocab), top_k,
                   jnp.int32(vocab)).astype(jnp.int32)
    pp = jnp.where((top_p > 0.0) & (top_p < 1.0), top_p,
                   jnp.float32(jnp.inf))
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_desc = jnp.where(jnp.arange(vocab) < kk, sorted_desc, neg)
    kthresh = jnp.take(sorted_desc, kk - 1, axis=-1)[..., None]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < pp], axis=-1)
    pthresh = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < jnp.maximum(kthresh, pthresh), neg, logits)


def draw(logits, t, *, temperature: float = 0.0, top_k: int = 0,
         top_p: float = 1.0, key=None, mask=None):
    """One token per row of ``logits [..., vocab]`` — ``gpt.generate``'s
    draw, verbatim: greedy argmax at ``temperature <= 0``, else a
    categorical sample from the temperature-scaled, top-k/top-p-filtered
    distribution under ``fold_in(key, t)`` (``t`` is the position of the
    token the logits were computed from, so every decode step draws from
    a distinct, reproducible stream). ``mask`` (bool ``[..., vocab]``)
    restricts the draw to True positions — constrained decoding; both
    the greedy argmax and the sampled branch honour it."""
    if temperature > 0.0:
        # temperature first: top_p must see the distribution actually
        # being sampled (standard warper order)
        scaled = filter_logits(logits / temperature, top_k, top_p,
                               mask=mask)
        return jax.random.categorical(
            jax.random.fold_in(key, t), scaled, axis=-1
        ).astype(jnp.int32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def draw_slots(logits, keys, t, temperature, top_k, top_p, masks=None):
    """Per-slot batched draw: ``logits [B, vocab]``; ``keys [B, 2]``
    (raw PRNG key data); ``t``/``temperature``/``top_k``/``top_p`` all
    ``[B]`` device vectors. ``masks`` (optional bool ``[B, vocab]``) is
    the per-slot constrained-decoding vocab mask — False positions are
    dropped to the dtype minimum before either branch, so an all-True
    row is bit-identical to the maskless path (the engine always passes
    masks; unconstrained slots ride all-True rows). Returns ``[B]
    int32``.

    Slot ``b``'s token is bit-identical to
    ``draw(logits[b:b+1], t[b], temperature=.., key=keys[b])[0]`` — the
    vmapped inner function sees a ``[1, vocab]`` row, so even the
    categorical's gumbel noise has the solo-generate shape, and greedy
    slots (``temperature <= 0``) take the argmax branch by ``where``
    (their sampled lane divides by a safe 1.0 and is discarded)."""

    def one(lg, key, tt, temp, kk, pp, mask=None):
        if mask is not None:
            lg = jnp.where(mask, lg, jnp.finfo(lg.dtype).min)
        safe = jnp.where(temp > 0, temp, jnp.float32(1.0))
        scaled = _filter_logits_traced(lg / safe, kk, pp)
        sampled = jax.random.categorical(
            jax.random.fold_in(key, tt), scaled, axis=-1)
        greedy = jnp.argmax(lg, axis=-1)
        return jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)

    if masks is None:
        return jax.vmap(one)(
            logits[:, None], keys, t, temperature, top_k, top_p)[:, 0]
    return jax.vmap(one)(
        logits[:, None], keys, t, temperature, top_k, top_p,
        masks[:, None])[:, 0]
