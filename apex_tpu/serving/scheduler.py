"""Continuous-batching scheduler — the host loop around the engine.

Policy lives here, device mechanics in :mod:`apex_tpu.serving.engine`:
a FIFO request queue with backpressure (``max_queue``), per-request
deadlines (queued requests expire in place; active slots are retired),
batched admission of queued requests into free slots
(:meth:`Engine.admit_many` — a burst drains in ~1 dispatch per ladder
group instead of one per request), a response stream
(:class:`apex_tpu.serving.request.StreamEvent`), and serving metrics —
TTFT, per-token latency, queue depth, slot occupancy, tokens/s —
aggregated via :class:`apex_tpu.profiler.LatencyStats` and emitted
through a :class:`apex_tpu.profiler.MetricsLogger` when one is given.

The decode loop is PIPELINED (``pipeline_depth``): each tick dispatches
the next chunk (``Engine.step_async``) before fetching the previous
one's tokens, so the host's fetch + event processing + admission
interval overlaps device compute — serial ``device + host`` becomes
``max(device, host)``. Depth 1 is the serial loop (dispatch, then fetch
immediately); depth d keeps up to d-1 chunks in flight between ticks.
Each in-flight chunk carries a snapshot of the slots that were live at
dispatch: a slot released while the chunk was in flight (finish seen in
an earlier chunk, or a deadline retire) has its columns dropped — the
device emits pad for done slots, and a retired slot's in-flight real
tokens belong to a request that already completed. Per-request token
streams are bit-identical at every depth (the pipelined-parity test);
only deadline OBSERVATION granularity coarsens with depth, exactly as
it already coarsens with ``decode_chunk``.

Observability (``apex_tpu.telemetry``): pass ``registry`` to count
admissions (by prefill bucket and admission-batch size) / finishes-by-
reason / tokens, gauge the in-flight pipeline depth, and observe TTFT +
per-token latency into SLO-bucketed histograms (scrapeable live via
``telemetry.http.MetricsServer``), and ``spans`` to record each
request's phase timeline (queued → prefill → first_token → decode
chunks → retired) plus ``engine.dispatch`` / ``engine.fetch`` /
``engine.admit`` host sections — the dispatch-vs-fetch split shows
exactly how much host time the pipeline hides. Both are pre-bound at
construction so the per-token hot path pays an attribute access and an
add, nothing more.

The boundary fix the engine relies on: a request whose prompt already
ends in its eos token completes at ``submit`` time with zero generated
tokens — it never occupies a slot (admitting it would burn
``max_tokens`` steps decoding past a finished sequence).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from apex_tpu import profiler
from apex_tpu.serving.engine import Admission, Engine, StepHandle
from apex_tpu.serving.request import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_REASONS,
    FINISH_TIMEOUT,
    Completion,
    Request,
    StreamEvent,
)
from apex_tpu.telemetry import spans as spans_mod


class QueueFull(RuntimeError):
    """Backpressure signal: the request queue is at ``max_queue``."""


class _RegistryMetrics:
    """Pre-bound registry handles — children resolved once here so the
    scheduler's per-token path never does a name/label lookup."""

    def __init__(self, registry, engine: Engine):
        self.queue_depth = registry.gauge(
            "serving_queue_depth", "requests waiting for a slot")
        self.active_slots = registry.gauge(
            "serving_active_slots", "decode slots currently occupied")
        registry.gauge(
            "serving_slots_total", "decode slots in the engine"
        ).set(engine.slots)
        self.inflight = registry.gauge(
            "serving_inflight_chunks",
            "decode chunks dispatched but not yet fetched (the pipeline "
            "depth actually in use)")
        self.submitted = registry.counter(
            "serving_requests_submitted_total", "requests accepted into "
            "the queue (or completed at submit)")
        self.admitted = registry.counter(
            "serving_requests_admitted_total",
            "requests prefilled into a slot")
        self.admit_dispatches = registry.counter(
            "serving_admit_dispatches_total",
            "batched admission dispatches (one compiled (bucket, k) "
            "program call each)")
        ab = registry.counter(
            "serving_admit_batch_requests_total",
            "requests admitted, by admission-batch size",
            labels=("size",))
        # pre-create every ladder rung so a scrape shows explicit zeros
        self.admit_batch = {k: ab.labels(size=str(k))
                            for k in engine.admit_batch_sizes}
        bk = registry.counter(
            "serving_prefill_bucket_requests_total",
            "requests admitted, by padded prefill bucket",
            labels=("bucket",))
        self.bucket = {b: bk.labels(bucket=str(b))
                       for b in engine.prompt_buckets}
        fin = registry.counter(
            "serving_requests_finished_total",
            "completed requests by finish reason", labels=("reason",))
        self.finished = {r: fin.labels(reason=r) for r in FINISH_REASONS}
        self.queue_expired = registry.counter(
            "serving_queue_expired_total",
            "requests that blew their deadline while still queued")
        self.tokens = registry.counter(
            "serving_tokens_emitted_total", "generated tokens streamed")
        self.steps = registry.counter(
            "serving_scheduler_steps_total", "scheduler ticks")
        self.ttft = registry.histogram(
            "serving_ttft_seconds", "arrival to first token")
        self.token_latency = registry.histogram(
            "serving_token_latency_seconds",
            "per-token steady-decode latency (chunk dispatch-to-fetch "
            "wall time / chunk tokens)")
        self.request_latency = registry.histogram(
            "serving_request_latency_seconds", "arrival to completion")


class _Active:
    """Host view of one occupied slot."""

    __slots__ = ("request", "tokens", "first_token_time")

    def __init__(self, request: Request):
        self.request = request
        self.tokens: List[int] = []
        self.first_token_time: Optional[float] = None


class Scheduler:
    """Drive an :class:`Engine` over a stream of requests.

    >>> sched = Scheduler(engine, pipeline_depth=2)
    >>> sched.submit(Request("r0", prompt, max_tokens=16))
    >>> sched.run_until_idle()
    >>> sched.completions["r0"].tokens

    ``clock`` is injectable (tests drive deadlines with a fake clock);
    it must be monotonic. ``metrics`` receives one record per step plus
    one per completion. ``pipeline_depth`` >= 2 overlaps host work with
    device decode (see module docstring); ``max_admit_batch`` caps how
    many queued requests one tick hands to ``Engine.admit_many`` (None
    = all that fit the free slots; 1 = serial single admits, the A/B
    baseline).
    """

    def __init__(self, engine: Engine, *, max_queue: int = 256,
                 metrics: Optional[profiler.MetricsLogger] = None,
                 registry=None, spans=None,
                 clock: Callable[[], float] = time.monotonic,
                 pipeline_depth: int = 1,
                 max_admit_batch: Optional[int] = None):
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth {pipeline_depth} must be >= 1 (1 = the "
                f"serial loop)")
        if max_admit_batch is not None and max_admit_batch < 1:
            raise ValueError(
                f"max_admit_batch {max_admit_batch} must be >= 1 or None")
        self.engine = engine
        self.max_queue = max_queue
        self.metrics = metrics
        self.clock = clock
        self.pipeline_depth = pipeline_depth
        self.max_admit_batch = max_admit_batch
        #: telemetry sinks (both optional): a telemetry.Registry the
        #: scheduler counts/observes into, and a telemetry.SpanRecorder
        #: receiving per-request phase marks + dispatch sections. The
        #: recorder's clock is slaved to the scheduler's so injected
        #: test clocks produce deterministic timelines.
        self.telemetry = (None if registry is None
                          else _RegistryMetrics(registry, engine))
        self.spans = spans
        if spans is not None:
            spans.clock = self.clock
        self.queue: Deque[Request] = collections.deque()
        self.active: Dict[int, _Active] = {}
        self.completions: Dict[str, Completion] = {}
        self.events: Deque[StreamEvent] = collections.deque()
        self.ttft_stats = profiler.LatencyStats()
        self.token_latency_stats = profiler.LatencyStats()
        self._free: List[int] = list(range(engine.slots))[::-1]
        #: chunks dispatched but not yet fetched, oldest first; each
        #: entry is (handle, slot->_Active snapshot at dispatch,
        #: dispatch time)
        self._inflight: Deque[
            Tuple[StepHandle, Dict[int, _Active], float]] = \
            collections.deque()
        self._steps = 0
        self._tokens_emitted = 0
        self._admitted_requests = 0
        self._admit_dispatches = 0
        self._started: Optional[float] = None
        # steady-decode split: wall time attributable to decode chunks
        # (dispatch-to-fetch, overlap-deduplicated so pipelined chunks
        # never double-count an interval) and the tokens they emitted —
        # TTFT (admission/prefill) excluded, so summary() can report
        # the two regimes separately
        self._decode_time = 0.0
        self._decode_tokens = 0
        self._decode_mark = float("-inf")

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request) -> None:
        """Enqueue ``request``; raises :class:`QueueFull` at capacity.
        Prompt-validity errors raise immediately; a prompt that already
        ends in the request's eos token completes here with zero
        generated tokens."""
        if request.request_id in self.completions or any(
                a.request.request_id == request.request_id
                for a in self.active.values()) or any(
                r.request_id == request.request_id for r in self.queue):
            raise ValueError(f"duplicate request_id {request.request_id!r}")
        request.sampling.validate()
        prompt = list(request.prompt)
        ecfg = self.engine.engine_cfg
        # the slot must fit prompt + at least one generated token
        limit = min(ecfg.max_prompt_len, ecfg.max_seq_len - 1)
        if not 1 <= len(prompt) <= limit:
            raise ValueError(
                f"prompt length {len(prompt)} outside [1, {limit}]")
        room = ecfg.max_seq_len - len(prompt)
        if not 1 <= request.max_tokens <= room:
            raise ValueError(
                f"max_tokens {request.max_tokens} outside [1, {room}] "
                f"for a {len(prompt)}-token prompt at max_seq_len "
                f"{ecfg.max_seq_len} — a clamped budget would silently "
                f"break solo-generate parity")
        eos = request.eos_token_id
        if eos is not None and not 0 <= eos < self.engine.cfg.vocab_size:
            raise ValueError(
                f"eos_token_id {eos} outside vocab "
                f"[0, {self.engine.cfg.vocab_size})")
        now = self.clock()
        request.arrival_time = now
        if (request.eos_token_id is not None
                and prompt[-1] == request.eos_token_id):
            if self.telemetry is not None:
                self.telemetry.submitted.inc()
            self._complete(request, [], FINISH_EOS, ttft=None, now=now)
            return
        if len(self.queue) >= self.max_queue:
            raise QueueFull(
                f"queue at capacity ({self.max_queue}); retry later")
        self.queue.append(request)
        if self.telemetry is not None:
            self.telemetry.submitted.inc()
            self.telemetry.queue_depth.set(len(self.queue))
        if self.spans is not None:
            self.spans.mark(request.request_id, spans_mod.PHASE_QUEUED)

    # -- the loop ----------------------------------------------------------

    def step(self) -> None:
        """One scheduler tick: expire deadlines, batch-admit queued
        requests into free slots, dispatch the next decode chunk if any
        slot is live, then fetch + unpack chunks down to the pipeline
        depth (ALL of them when nothing was dispatched — the drain
        path, so a tick always makes progress). At depth 1 this is the
        serial loop: dispatch, fetch, unpack. Deadlines and admissions
        are checked between chunks — the ``decode_chunk`` admission-
        latency/throughput tradeoff, now also the pipeline-depth one."""
        now = self.clock()
        if self._started is None:
            self._started = now
        self._expire(now)
        self._admit_queued(now)
        dispatched = False
        if self._dispatchable():
            self._dispatch_chunk()
            dispatched = True
        keep = self.pipeline_depth - 1 if dispatched else 0
        while len(self._inflight) > keep:
            self._collect_oldest()
        self._steps += 1
        if self.telemetry is not None:
            self.telemetry.steps.inc()
            self.telemetry.queue_depth.set(len(self.queue))
            self.telemetry.active_slots.set(len(self.active))
        if self.metrics is not None:
            elapsed = max(self.clock() - self._started, 1e-9)
            self.metrics.log(self._steps, {
                "queue_depth": len(self.queue),
                "slot_occupancy": len(self.active) / self.engine.slots,
                "tokens_emitted": self._tokens_emitted,
                "tokens_per_sec": self._tokens_emitted / elapsed,
            })

    def drain(self) -> None:
        """Fetch + unpack every in-flight chunk (pipeline drain): after
        this, ``events``/``completions`` reflect all dispatched work."""
        while self._inflight:
            self._collect_oldest()

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        """Step until queue, slots, and the pipeline are empty (offline
        batch mode)."""
        steps = 0
        while self.queue or self.active or self._inflight:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"not idle after {max_steps} steps — live slots "
                    f"{sorted(self.active)}, queue {len(self.queue)}, "
                    f"{len(self._inflight)} chunks in flight")

    def pop_events(self) -> List[StreamEvent]:
        """Drain the response stream."""
        out = list(self.events)
        self.events.clear()
        return out

    # -- internals ---------------------------------------------------------

    def _dispatchable(self) -> bool:
        """Whether dispatching another chunk can produce ANY real
        token: some active slot must have token budget left beyond the
        columns already in flight for it. Without this guard a deep
        pipeline burns a guaranteed-all-pad chunk at every wave of
        finishes (the host only learns a slot died when it fetches the
        chunk that killed it). Early-eos finishes stay speculative —
        the host cannot predict them, so a chunk may still carry some
        pad lanes, exactly like a mid-chunk finish under
        ``decode_chunk`` — but a chunk that CANNOT pay for itself is
        never dispatched."""
        if not self.active:
            return False
        if not self._inflight:
            return True
        cols: Dict[int, int] = {}
        chunk = self.engine.engine_cfg.decode_chunk
        for _, snapshot, _ in self._inflight:
            for slot, act in snapshot.items():
                if self.active.get(slot) is act:
                    cols[slot] = cols.get(slot, 0) + chunk
        return any(
            len(act.tokens) + cols.get(slot, 0) < act.request.max_tokens
            for slot, act in self.active.items())

    def _dispatch_chunk(self) -> None:
        t0 = self.clock()
        handle = self.engine.step_async()
        t1 = self.clock()
        if self.spans is not None:
            # the host-side cost of getting the chunk onto the device —
            # the half of the old engine.step section the pipeline
            # cannot hide
            self.spans.section_at("engine.dispatch", t0, t1)
        # snapshot the live slots: by the time this chunk is fetched,
        # some may have been released (finish seen in an earlier chunk,
        # deadline retire) and their columns must be dropped
        self._inflight.append((handle, dict(self.active), t0))
        if self.telemetry is not None:
            self.telemetry.inflight.set(len(self._inflight))

    def _collect_oldest(self) -> None:
        handle, snapshot, t_dispatch = self._inflight.popleft()
        t0 = self.clock()
        tokens, finished = handle.fetch()
        now = self.clock()
        tele = self.telemetry
        if tele is not None:
            tele.inflight.set(len(self._inflight))
        if self.spans is not None:
            # the blocking wait for the chunk's value — under pipelining
            # this shrinks toward zero while engine.dispatch stays put
            self.spans.section_at("engine.fetch", t0, now)
            for slot, act in snapshot.items():
                if self.active.get(slot) is act:
                    self.spans.mark(act.request.request_id,
                                    spans_mod.PHASE_DECODE)
        n_cols = tokens.shape[1]
        # in-flight latency of this chunk (dispatch -> value); the
        # decode-time split dedups the overlap so pipelined chunks
        # don't double-count wall time
        per_tok = max(now - t_dispatch, 0.0) / n_cols
        self._decode_time += now - max(self._decode_mark, t_dispatch)
        self._decode_mark = now
        for j in range(n_cols):
            for slot, act in snapshot.items():
                # a slot released since dispatch (earlier chunk/column
                # finish, or a deadline retire landing mid-flight) is
                # skipped: the device emits pad for done lanes, and a
                # retired request's in-flight tokens belong to a
                # completion that already closed
                if self.active.get(slot) is not act:
                    continue
                tok = int(tokens[slot, j])
                act.tokens.append(tok)
                self._tokens_emitted += 1
                self._decode_tokens += 1
                self.token_latency_stats.add(per_tok)
                if tele is not None:
                    tele.tokens.inc()
                    tele.token_latency.observe(per_tok)
                done = bool(finished[slot, j])
                reason = None
                if done:
                    eos = act.request.eos_token_id
                    reason = (FINISH_EOS
                              if eos is not None and tok == eos
                              else FINISH_LENGTH)
                self.events.append(StreamEvent(
                    act.request.request_id, tok, done, reason))
                if done:
                    self._release(slot, reason)

    def _expire(self, now: float) -> None:
        self.queue = collections.deque(
            r for r in self.queue
            if not self._expire_queued(r, now))
        for slot in list(self.active):
            act = self.active[slot]
            dl = act.request.deadline
            if dl is not None and now >= dl:
                self.engine.retire(slot)
                self.events.append(StreamEvent(
                    act.request.request_id, None, True, FINISH_TIMEOUT))
                self._release(slot, FINISH_TIMEOUT)

    def _expire_queued(self, request: Request, now: float) -> bool:
        dl = request.deadline
        if dl is None or now < dl:
            return False
        if self.telemetry is not None:
            self.telemetry.queue_expired.inc()
        self._complete(request, [], FINISH_TIMEOUT, ttft=None, now=now)
        self.events.append(StreamEvent(
            request.request_id, None, True, FINISH_TIMEOUT))
        return True

    def _admit_queued(self, now: float) -> None:
        while self._free and self.queue:
            n = min(len(self._free), len(self.queue))
            if self.max_admit_batch is not None:
                n = min(n, self.max_admit_batch)
            reqs = [self.queue.popleft() for _ in range(n)]
            slots = [self._free.pop() for _ in range(n)]
            if self.spans is not None:
                for r, slot in zip(reqs, slots):
                    self.spans.mark(r.request_id, spans_mod.PHASE_PREFILL,
                                    note=f"slot {slot}")
                t_admit = self.clock()
            results = self.engine.admit_many([
                Admission(slot=slot, prompt=r.prompt,
                          max_tokens=r.max_tokens,
                          temperature=r.sampling.temperature,
                          top_k=r.sampling.top_k, top_p=r.sampling.top_p,
                          seed=r.sampling.seed,
                          eos_token_id=r.eos_token_id)
                for r, slot in zip(reqs, slots)])
            t_first = self.clock()
            n_groups = results[-1].group + 1
            self._admitted_requests += n
            self._admit_dispatches += n_groups
            if self.spans is not None:
                self.spans.section_at("engine.admit", t_admit, t_first)
            tele = self.telemetry
            if tele is not None:
                tele.admit_dispatches.inc(n_groups)
                tele.queue_depth.set(len(self.queue))
            for r, slot, res in zip(reqs, slots, results):
                act = _Active(r)
                act.first_token_time = t_first
                act.tokens.append(res.first_token)
                self._tokens_emitted += 1
                self.ttft_stats.add(t_first - r.arrival_time)
                if self.spans is not None:
                    self.spans.mark(r.request_id,
                                    spans_mod.PHASE_FIRST_TOKEN)
                if tele is not None:
                    tele.admitted.inc()
                    tele.tokens.inc()
                    tele.ttft.observe(t_first - r.arrival_time)
                    tele.admit_batch[res.batch_size].inc()
                    tele.bucket[res.bucket].inc()
                reason = None
                if res.finished:
                    reason = FINISH_EOS if res.hit_eos else FINISH_LENGTH
                self.events.append(StreamEvent(
                    r.request_id, res.first_token, res.finished, reason))
                self.active[slot] = act
                if res.finished:
                    self._release(slot, reason)

    def _release(self, slot: int, reason: str) -> None:
        act = self.active.pop(slot)
        self._free.append(slot)
        now = self.clock()
        ttft = (None if act.first_token_time is None
                else act.first_token_time - act.request.arrival_time)
        self._complete(act.request, act.tokens, reason, ttft=ttft, now=now)

    def _complete(self, request: Request, tokens: List[int], reason: str,
                  *, ttft: Optional[float], now: float) -> None:
        arrival = request.arrival_time if request.arrival_time is not None \
            else now
        comp = Completion(request.request_id, list(tokens), reason,
                          ttft=ttft, latency=now - arrival)
        self.completions[request.request_id] = comp
        if reason == FINISH_EOS and not tokens:
            # eos-terminal prompt: completes at submit, emits only the
            # finished event (no token)
            self.events.append(StreamEvent(
                request.request_id, None, True, reason))
        if self.telemetry is not None:
            self.telemetry.finished[reason].inc()
            self.telemetry.request_latency.observe(comp.latency)
        if self.spans is not None:
            self.spans.mark(request.request_id, spans_mod.PHASE_RETIRED,
                            note=reason)
        if self.metrics is not None:
            # no value for "no first token" — a -1.0 ttft sentinel
            # silently poisons any downstream mean/percentile, so the
            # key is simply absent for zero-token completions
            rec = {
                "completed": 1.0,
                "n_tokens": float(len(tokens)),
                "latency_s": comp.latency,
            }
            if ttft is not None:
                rec["ttft_s"] = ttft
            self.metrics.log(self._steps, rec)

    # -- reporting ---------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        """Aggregate serving metrics: throughput + latency percentiles
        (the bench's one JSON line)."""
        elapsed = None
        if self._started is not None:
            elapsed = max(self.clock() - self._started, 1e-9)
        out = {
            "requests_completed": float(len(self.completions)),
            "tokens_emitted": float(self._tokens_emitted),
            "steps": float(self._steps),
            "admitted_requests": float(self._admitted_requests),
            # batched admission's amortisation, directly: requests
            # prefilled per compiled admission dispatch
            "admit_dispatches": float(self._admit_dispatches),
            "pipeline_depth": float(self.pipeline_depth),
        }
        if elapsed:
            out["tokens_per_sec"] = self._tokens_emitted / elapsed
        if self._decode_time > 0:
            # the steady-state half of the TTFT-vs-decode split: tokens
            # emitted by decode chunks per second of (overlap-dedup'd)
            # wall time spent on them (admission/prefill — the TTFT
            # side — excluded)
            out["decode_tokens_per_sec"] = (
                self._decode_tokens / self._decode_time)
            out["decode_tokens"] = float(self._decode_tokens)
            out["decode_time_s"] = self._decode_time
        for name, stats in (("ttft", self.ttft_stats),
                            ("token_latency", self.token_latency_stats)):
            for k, v in stats.summary().items():
                out[f"{name}_{k}"] = v
        return out
