"""Continuous-batching scheduler — the host loop around the engine.

Policy lives here, device mechanics in :mod:`apex_tpu.serving.engine`:
a FIFO request queue with backpressure (``max_queue``), per-request
deadlines (queued requests expire in place; active slots are retired),
batched admission of queued requests into free slots
(:meth:`Engine.admit_many` — a burst drains in ~1 dispatch per ladder
group instead of one per request), a response stream
(:class:`apex_tpu.serving.request.StreamEvent`), and serving metrics —
TTFT, per-token latency, queue depth, slot occupancy, tokens/s —
aggregated via :class:`apex_tpu.profiler.LatencyStats` and emitted
through a :class:`apex_tpu.profiler.MetricsLogger` when one is given.

The decode loop is PIPELINED (``pipeline_depth``): each tick dispatches
the next chunk (``Engine.step_async``) before fetching the previous
one's tokens, so the host's fetch + event processing + admission
interval overlaps device compute — serial ``device + host`` becomes
``max(device, host)``. Depth 1 is the serial loop (dispatch, then fetch
immediately); depth d keeps up to d-1 chunks in flight between ticks.
Each in-flight chunk carries a snapshot of the slots that were live at
dispatch: a slot released while the chunk was in flight (finish seen in
an earlier chunk, or a deadline retire) has its columns dropped — the
device emits pad for done slots, and a retired slot's in-flight real
tokens belong to a request that already completed. Per-request token
streams are bit-identical at every depth (the pipelined-parity test);
only deadline OBSERVATION granularity coarsens with depth, exactly as
it already coarsens with ``decode_chunk``.

Fault tolerance (:mod:`apex_tpu.serving.resilience`): an exception
escaping an engine seam, an invalid-token (NaN-poisoned) batch, or a
hung dispatch no longer takes the engine down. The failing chunk/call
is quarantined, the engine's donated buffers are rebuilt from the
compiled ``init`` program, and every interrupted request is
deterministically REPLAYED from its prompt (generation is per-request
deterministic, so the replayed stream is bit-identical and
already-streamed tokens are re-derived silently). Requests in the
fault's blast radius get bounded retries with exponential backoff and
``error`` stream events; retry exhaustion completes them with the
``error`` finish reason. Overload protection: deadline-aware admission
shedding (queue depth × measured chunk latency vs the deadline — shed
NOW instead of rotting then expiring), structured :class:`QueueFull`
with a retry-after hint, and a fetch watchdog flagging hung dispatches.
``self.health`` is the ``ok → degraded → draining → failed`` state
machine, scrapeable live via
``telemetry.http.MetricsServer(health=sched.health.healthz)``.

Observability (``apex_tpu.telemetry``): pass ``registry`` to count
admissions (by prefill bucket and admission-batch size) / finishes-by-
reason / tokens / faults / retries / rebuilds / sheds, gauge the
in-flight pipeline depth and health state, and observe TTFT + per-token
latency into SLO-bucketed histograms (scrapeable live via
``telemetry.http.MetricsServer``), and ``spans`` to record each
request's phase timeline (queued → prefill → first_token → decode
chunks → retired, plus ``error`` marks) and ``engine.dispatch`` /
``engine.fetch`` / ``engine.admit`` / ``engine.rebuild`` host sections.
Both are pre-bound at construction so the per-token hot path pays an
attribute access and an add, nothing more.

Black box (``apex_tpu.telemetry.flightrec``): pass ``recorder`` to log
every load-bearing host decision (submits/sheds, admit dispatches,
chunk dispatch/fetch, spec-gate flips, fault injection/detection,
rebuild/replay brackets, watchdog and guard alarms, health
transitions) into a bounded ring of O(1) tuple appends, and
``bundle_dir`` to auto-dump an atomic self-contained post-mortem
bundle on any fault detection, guard alarm, watchdog trip, or terminal
failure — ``python -m apex_tpu.telemetry.replay <bundle>`` rebuilds
the run from it and checks the replayed streams bit-identical, and
``--report`` renders the incident timeline with no jax installed.

The boundary fix the engine relies on: a request whose prompt already
ends in its eos token completes at ``submit`` time with zero generated
tokens — it never occupies a slot (admitting it would burn
``max_tokens`` steps decoding past a finished sequence).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

from apex_tpu import profiler
from apex_tpu.serving import journal as journal_mod
from apex_tpu.serving.engine import (
    Admission,
    ChunkedAdmission,
    Engine,
    StepHandle,
)
from apex_tpu.serving.pages import PagesExhausted
from apex_tpu.serving.request import (
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_REASONS,
    FINISH_STOP,
    FINISH_TIMEOUT,
    Completion,
    Request,
    StopMatcher,
    StreamEvent,
)
from apex_tpu.serving.resilience import (
    HEALTH_DRAINING,
    HEALTH_FAILED,
    KIND_FLOOD,
    EngineFailed,
    HealthMonitor,
    ResilienceConfig,
)
from apex_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenancyConfig,
    TenantBook,
    TenantThrottled,
)
from apex_tpu.serving.tuner import Controller, TunerConfig, ewma
from apex_tpu.telemetry import flightrec as flightrec_mod
from apex_tpu.telemetry import spans as spans_mod
from apex_tpu.telemetry.ring import Ring
from apex_tpu.telemetry.slo import (
    METRICS as SLO_METRICS,
    STATE_CODE as SLO_STATE_CODE,
    SLOConfig,
    SLOMonitor,
    SLOObjective,
)

#: fault causes the scheduler can detect (label values of
#: ``serving_faults_detected_total``, pre-created so scrapes show
#: explicit zeros)
FAULT_CAUSES = ("admit", "dispatch", "fetch", "retire", "invalid_token")

#: shed reasons (label values of ``serving_requests_shed_total``)
SHED_REASONS = ("queue_full", "deadline", "tenant_rate")


@dataclasses.dataclass(frozen=True)
class SpecGateConfig:
    """Policy knobs of the speculative-decoding payoff gate (only
    meaningful on an engine with ``EngineConfig.spec_k > 0``).

    The gate is the docs/DESIGN.md "Serving round 3" lesson applied to
    speculation: a speculative chunk only pays when the drafts it
    verifies actually land, so the scheduler measures BOTH compiled
    variants' chunk wall times and an acceptance EWMA, and dispatches
    the spec variant only while::

        EWMA(tokens emitted per wave)  >  wall_spec / wall_plain

    (the break-even: a spec wave costs ``wall_spec / decode_chunk``
    and emits ``tpw`` tokens; a plain step costs ``wall_plain /
    decode_chunk`` per token — spec wins iff tpw clears the wall
    ratio). Both variants are pre-warmed, so switching never
    recompiles."""

    #: weight of the newest acceptance sample in the EWMA
    ewma_alpha: float = 0.3
    #: a CLOSED gate reopens only when the EWMA clears break-even by
    #: this factor (hysteresis — an open gate closes at 1.0x)
    margin: float = 1.05
    #: re-probe cadence, symmetric in both directions: a CLOSED gate
    #: sends one speculative chunk per this many plain chunks (a
    #: workload that turns repetitive reopens the gate), and an OPEN
    #: gate sends one plain chunk per this many speculative chunks so
    #: ``wall_plain`` tracks the growing attention cost instead of
    #: freezing at short-context values (a stale baseline inflates the
    #: break-even and flaps the gate closed on exactly the
    #: long-generation workloads speculation targets)
    probe_every: int = 40
    #: speculative chunks to measure before the gate decides at all
    min_probe_chunks: int = 2


#: ``serving_spec_gate_state`` gauge values
GATE_CLOSED, GATE_MEASURING, GATE_OPEN = 0.0, 1.0, 2.0


class _SpecGate:
    """The live payoff-gate state machine behind
    :class:`SpecGateConfig` — wall-time EWMAs for both chunk variants,
    the acceptance (tokens-per-wave) EWMA, and the open/closed/probe
    decision. Pure host arithmetic; the decision only picks which
    pre-warmed compiled variant the next dispatch uses."""

    __slots__ = ("cfg", "spec_k", "accept_ewma", "wall_spec",
                 "wall_plain", "spec_chunks", "plain_since_probe",
                 "spec_since_plain", "_open")

    def __init__(self, cfg: SpecGateConfig, spec_k: int):
        self.cfg = cfg
        self.spec_k = spec_k
        self.accept_ewma = 0.0      # tokens per wave (1 .. spec_k + 1)
        self.wall_spec = 0.0
        self.wall_plain = 0.0
        self.spec_chunks = 0
        self.plain_since_probe = 0
        self.spec_since_plain = 0
        self._open = True           # optimistic until measured

    def _ewma(self, prev: float, sample: float) -> float:
        return ewma(prev, sample, self.cfg.ewma_alpha)

    def break_even(self) -> float:
        """Tokens per wave a spec chunk must emit to match the plain
        variant's cost — ``wall_spec / wall_plain`` (0.0 until both
        are measured)."""
        if self.wall_spec <= 0.0 or self.wall_plain <= 0.0:
            return 0.0
        return self.wall_spec / self.wall_plain

    def want_spec(self, spec_inflight: int = 0) -> bool:
        """Which variant the NEXT chunk should use. ``spec_inflight``
        is the count of speculative chunks dispatched but not yet
        fetched: the fetch-side counters reset only when a probe LANDS,
        so until the gate has measured its way open, probes are
        serialized — at most one speculative chunk in flight — lest a
        pipelined scheduler multiply the documented one-chunk probe
        overhead by its depth."""
        if self.wall_plain == 0.0:
            return False            # measure the plain baseline first
        measuring = self.spec_chunks < self.cfg.min_probe_chunks
        if (measuring or not self._open) and spec_inflight > 0:
            return False            # one probe at a time
        if measuring:
            return True             # measuring the spec side
        if self._open:
            # plain-refresh probe: once per probe_every spec chunks the
            # open gate re-measures wall_plain (see SpecGateConfig)
            return self.spec_since_plain < self.cfg.probe_every
        return self.plain_since_probe >= self.cfg.probe_every

    def observe_plain(self, wall: float) -> None:
        self.wall_plain = self._ewma(self.wall_plain, wall)
        self.plain_since_probe += 1
        self.spec_since_plain = 0

    def observe_spec(self, wall: float,
                     tokens_per_wave: Optional[float]) -> None:
        self.wall_spec = self._ewma(self.wall_spec, wall)
        self.spec_chunks += 1
        self.plain_since_probe = 0
        self.spec_since_plain += 1
        if tokens_per_wave is not None:
            self.accept_ewma = self._ewma(self.accept_ewma,
                                          tokens_per_wave)
        if self.accept_ewma == 0.0:
            # no acceptance sample has EVER landed (every probe chunk's
            # rows were retired mid-flight) — deciding now would close
            # the gate on zero data; keep measuring instead. A real
            # sample can never be 0.0 (a live wave always emits >= 1
            # token), so this is an unambiguous never-measured sentinel
            return
        be = self.break_even()
        if be <= 0.0 or self.spec_chunks < self.cfg.min_probe_chunks:
            return
        if self._open:
            self._open = self.accept_ewma > be
        else:
            # hysteresis: reopening needs the margin
            self._open = self.accept_ewma > be * self.cfg.margin

    def state(self) -> float:
        """Gauge value: 2 open, 1 measuring, 0 closed."""
        if (self.wall_plain == 0.0
                or self.spec_chunks < self.cfg.min_probe_chunks
                or self.accept_ewma == 0.0):
            return GATE_MEASURING
        return GATE_OPEN if self._open else GATE_CLOSED


class QueueFull(RuntimeError):
    """Backpressure signal: the request queue is at ``max_queue``.
    Carries structured overload context so a client (or gateway) can
    back off intelligently instead of parsing the message:
    ``queue_depth`` is the depth at rejection time and
    ``retry_after_s`` estimates when the queue will have drained
    (depth × measured chunk latency; 0.0 before any chunk has been
    measured)."""

    def __init__(self, message: str, *, queue_depth: int = 0,
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s


class _RegistryMetrics:
    """Pre-bound registry handles — children resolved once here so the
    scheduler's per-token path never does a name/label lookup."""

    def __init__(self, registry, engine: Engine):
        self.queue_depth = registry.gauge(
            "serving_queue_depth", "requests waiting for a slot")
        self.active_slots = registry.gauge(
            "serving_active_slots", "decode slots currently occupied")
        registry.gauge(
            "serving_slots_total", "decode slots in the engine"
        ).set(engine.slots)
        self.inflight = registry.gauge(
            "serving_inflight_chunks",
            "decode chunks dispatched but not yet fetched (the pipeline "
            "depth actually in use)")
        self.submitted = registry.counter(
            "serving_requests_submitted_total", "requests accepted into "
            "the queue (or completed at submit)")
        self.admitted = registry.counter(
            "serving_requests_admitted_total",
            "requests prefilled into a slot")
        self.admit_dispatches = registry.counter(
            "serving_admit_dispatches_total",
            "batched admission dispatches (one compiled (bucket, k) "
            "program call each)")
        ab = registry.counter(
            "serving_admit_batch_requests_total",
            "requests admitted, by admission-batch size",
            labels=("size",))
        # pre-create every ladder rung so a scrape shows explicit zeros
        self.admit_batch = {k: ab.labels(size=str(k))
                            for k in engine.admit_batch_sizes}
        bk = registry.counter(
            "serving_prefill_bucket_requests_total",
            "requests admitted, by padded prefill bucket",
            labels=("bucket",))
        self.bucket = {b: bk.labels(bucket=str(b))
                       for b in engine.prompt_buckets}
        fin = registry.counter(
            "serving_requests_finished_total",
            "completed requests by finish reason", labels=("reason",))
        self.finished = {r: fin.labels(reason=r) for r in FINISH_REASONS}
        self.queue_expired = registry.counter(
            "serving_queue_expired_total",
            "requests that blew their deadline while still queued")
        self.tokens = registry.counter(
            "serving_tokens_emitted_total", "generated tokens streamed")
        self.steps = registry.counter(
            "serving_scheduler_steps_total", "scheduler ticks")
        self.ttft = registry.histogram(
            "serving_ttft_seconds", "arrival to first token")
        self.token_latency = registry.histogram(
            "serving_token_latency_seconds",
            "per-token steady-decode latency (chunk dispatch-to-fetch "
            "wall time / chunk tokens)")
        self.request_latency = registry.histogram(
            "serving_request_latency_seconds", "arrival to completion")
        # -- resilience (apex_tpu.serving.resilience) -------------------
        flt = registry.counter(
            "serving_faults_detected_total",
            "faults detected at engine seams, by cause",
            labels=("cause",))
        self.faults = {c: flt.labels(cause=c) for c in FAULT_CAUSES}
        shed = registry.counter(
            "serving_requests_shed_total",
            "requests rejected/shed by overload protection, by reason",
            labels=("reason",))
        self.shed = {r: shed.labels(reason=r) for r in SHED_REASONS}
        self.retries = registry.counter(
            "serving_retries_total",
            "fault-affected requests scheduled for re-admission")
        self.rebuilds = registry.counter(
            "serving_rebuilds_total",
            "cache/state buffer rebuilds after a fault")
        self.watchdog = registry.counter(
            "serving_watchdog_trips_total",
            "decode chunks whose dispatch-to-fetch wall time exceeded "
            "the watchdog timeout (hung dispatches)")
        self.replayed = registry.counter(
            "serving_replayed_tokens_total",
            "tokens re-derived (and suppressed) during deterministic "
            "replay after a rebuild")
        # -- KV-cache capacity (quantized cache + prefix pool) ------------
        registry.gauge(
            "serving_kv_cache_bytes",
            "device bytes held by the slot KV cache (quantized data + "
            "scale planes under a quantized kv_cache_dtype)"
        ).set(engine.cache_bytes())
        # -- paged KV cache (EngineConfig.page_size) ----------------------
        # pre-created even for contiguous engines (explicit zeros in
        # scrapes, same convention as every ladder counter above)
        self.pages_in_use = registry.gauge(
            "serving_pages_in_use",
            "KV-cache pages currently allocated (paged layout; 0 under "
            "the contiguous layout)")
        self.pages_free = registry.gauge(
            "serving_pages_free",
            "KV-cache pages on the free list (paged layout)")
        self.pages_shared = registry.gauge(
            "serving_pages_shared",
            "KV-cache pages pinned by more than one holder — "
            "copy-on-write prefix pages with live sharers")
        self.page_fragmentation = registry.gauge(
            "serving_page_fragmentation",
            "internal fragmentation of the allocated pages: 1 - "
            "used_tokens / (pages_in_use * page_size)")
        self.page_share_hits = registry.counter(
            "serving_page_share_hits_total",
            "admissions that mapped a registered prefix's pages "
            "copy-on-write instead of copying prefix K/V bytes")
        self.pages_exhausted = registry.counter(
            "serving_pages_exhausted_total",
            "admission waves deferred because the page pool had fewer "
            "free pages than the head request needed (backpressure — "
            "the request stays queued)")
        # -- host-swap oversubscription (EngineConfig.host_swap) ----------
        self.pages_swapped = registry.gauge(
            "serving_pages_swapped",
            "KV-cache pages parked in the host-RAM swap tier (paused "
            "conversations' private pages; 0 without host_swap)")
        self.swap_bytes = registry.gauge(
            "serving_swap_bytes",
            "host-RAM bytes held by parked swap payloads (storage-form "
            "page blocks plus state rows)")
        self.preemptions = registry.counter(
            "serving_preemptions_total",
            "active requests preempted under page pressure (the WFQ "
            "victim's pages freed; its stream resumes bit-identically "
            "via fault replay)")
        self.chunked_chunks = registry.counter(
            "serving_chunked_prefill_chunks_total",
            "chunked-prefill chunk forwards dispatched (long-prompt "
            "admissions interleaved with decode waves)")
        self.chunked_admissions = registry.counter(
            "serving_chunked_admissions_total",
            "requests admitted through the chunked-prefill path")
        self.prefix_hits = registry.counter(
            "serving_prefix_hits_total",
            "submitted requests that matched a pooled shared prefix "
            "(admission pays the tail bucket only)")
        self.prefix_misses = registry.counter(
            "serving_prefix_misses_total",
            "submitted requests that missed the prefix pool (cold "
            "prefill at the full prompt bucket)")
        # -- speculative decoding (EngineConfig.spec_k) -------------------
        self.spec_drafted = registry.counter(
            "serving_spec_drafted_total",
            "draft tokens proposed to the speculative verify forward")
        self.spec_accepted = registry.counter(
            "serving_spec_accepted_total",
            "draft tokens the target's verification accepted (emitted "
            "beyond the one-per-wave baseline)")
        self.spec_gate = registry.gauge(
            "serving_spec_gate_state",
            "speculation payoff gate: 2 open, 1 measuring, 0 closed")
        self.spec_accept_ewma = registry.gauge(
            "serving_spec_acceptance_ewma",
            "EWMA of tokens emitted per speculative wave (the gate "
            "compares it to the measured wall_spec/wall_plain "
            "break-even)")
        # -- multi-tenant serving (serving.tenancy) -----------------------
        # tenant-labeled children are created lazily per tenant (the
        # label set is the live tenant population, not a config-time
        # ladder) and cached so the per-token path pays a dict get
        tt = registry.counter(
            "serving_tenant_tokens_total",
            "generated tokens streamed, by tenant", labels=("tenant",))
        ta = registry.counter(
            "serving_tenant_admissions_total",
            "requests prefilled into a slot, by tenant",
            labels=("tenant",))
        ts = registry.counter(
            "serving_tenant_sheds_total",
            "requests shed or rate-throttled, by tenant and reason",
            labels=("tenant", "reason"))
        tq = registry.gauge(
            "serving_tenant_queue_depth",
            "queued requests, by tenant", labels=("tenant",))
        self._tenant_families = (tt, ta, ts, tq)
        self._tenant_children: Dict[str, Dict[str, Any]] = {}
        # -- self-tuning control plane (serving.tuner) --------------------
        # pre-created even without a tuner (explicit zeros in scrapes,
        # the ladder-counter convention); per-knob children are bound
        # by the scheduler once the declared knobs are known
        self.tuner_state = registry.gauge(
            "serving_tuner_state",
            "self-tuning controller: 0 frozen, 1 measuring, 2 steady, "
            "3 probing")
        self._tuner_knob_family = registry.gauge(
            "serving_tuner_knob",
            "incumbent operating-point value per tuned knob",
            labels=("knob",))
        self._tuner_switch_family = registry.counter(
            "serving_tuner_switches_total",
            "operating-point switches the controller committed, by "
            "knob", labels=("knob",))
        self.tuner_knob: Dict[str, Any] = {}
        self.tuner_switches: Dict[str, Any] = {}
        # -- SLO observatory (telemetry.slo) ------------------------------
        # pre-created even without an SLO config (explicit zeros in
        # scrapes); quantile/objective children are bound lazily by
        # the scheduler's gauge refresh once the monitor exists
        self._slo_quantile_family = registry.gauge(
            "serving_slo_quantile_seconds",
            "streaming sketch-backed latency quantiles, by metric "
            "(ttft/token_latency/queue_wait/e2e) and quantile "
            "(p50/p95/p99)", labels=("metric", "quantile"))
        self._slo_burn_family = registry.gauge(
            "serving_slo_burn_rate",
            "error-budget burn rate per objective and window (1.0 = "
            "consuming the budget exactly on schedule)",
            labels=("objective", "window"))
        self._slo_state_family = registry.gauge(
            "serving_slo_state",
            "burn-rate machine state per objective: 0 ok, 1 warning, "
            "2 burning", labels=("objective",))
        self._slo_budget_family = registry.gauge(
            "serving_slo_budget_remaining",
            "fraction of the error budget left per objective (1 "
            "untouched, 0 exhausted, negative = overrun)",
            labels=("objective",))
        self._slo_alert_family = registry.counter(
            "serving_slo_alerts_total",
            "burn-rate alerts fired (transitions into warning or "
            "burning), by objective and state",
            labels=("objective", "state"))
        self.slo_quantile: Dict[Tuple[str, str], Any] = {}
        self.slo_children: Dict[str, Dict[str, Any]] = {}
        # -- durable request journal (serving.journal) --------------------
        # pre-created even without a journal (explicit zeros in
        # scrapes, the ladder-counter convention); refreshed at the
        # scheduler's fetch-boundary commit
        self.journal_appends = registry.counter(
            "serving_journal_appends_total",
            "write-ahead journal records appended (submit/extend/"
            "finish/park/resume/registrations)")
        self.journal_rotations = registry.counter(
            "serving_journal_rotations_total",
            "journal segments sealed and rotated")
        self.journal_compactions = registry.counter(
            "serving_journal_compactions_total",
            "journal compactions (finished requests dropped, live "
            "state rewritten into one fresh segment)")
        self.journal_fsync = registry.counter(
            "serving_journal_fsync_seconds",
            "wall seconds spent in journal fsync calls — the "
            "durability tax the fsync policy prices")
        self.journal_bytes = registry.gauge(
            "serving_journal_bytes",
            "write-ahead journal bytes on disk across all segments")
        self.journal_lag = registry.gauge(
            "serving_journal_lag_bytes",
            "journal bytes appended since the last fsync — what a "
            "crash right now could lose to the page cache")
        self.journal_recovered = registry.counter(
            "serving_journal_recovered_total",
            "unfinished requests resubmitted from a journal during "
            "crash recovery (replay_into/recover_scheduler)")

    def tenant(self, t: str) -> Dict[str, Any]:
        """Cached per-tenant metric children (created on first
        sight)."""
        ch = self._tenant_children.get(t)
        if ch is None:
            tt, ta, ts, tq = self._tenant_families
            ch = self._tenant_children[t] = {
                "tokens": tt.labels(tenant=t),
                "admitted": ta.labels(tenant=t),
                "queue": tq.labels(tenant=t),
                "shed": {r: ts.labels(tenant=t, reason=r)
                         for r in SHED_REASONS},
            }
        return ch

    def bind_tuner(self, knobs) -> None:
        """Pre-create the per-knob children for the declared ladder
        (explicit zeros in scrapes, like every ladder counter)."""
        for k in knobs:
            self.tuner_knob[k] = self._tuner_knob_family.labels(knob=k)
            self.tuner_switches[k] = \
                self._tuner_switch_family.labels(knob=k)

    def bind_slo(self, metrics, objective_keys) -> None:
        """Pre-create the SLO children for the declared surface —
        quantile gauges per metric and burn/state/budget/alert
        children per objective (explicit zeros in scrapes)."""
        for m in metrics:
            for q in ("p50", "p95", "p99"):
                self.slo_quantile[(m, q)] = \
                    self._slo_quantile_family.labels(metric=m,
                                                     quantile=q)
        for k in objective_keys:
            self.slo_children[k] = {
                "fast": self._slo_burn_family.labels(objective=k,
                                                     window="fast"),
                "slow": self._slo_burn_family.labels(objective=k,
                                                     window="slow"),
                "state": self._slo_state_family.labels(objective=k),
                "budget": self._slo_budget_family.labels(objective=k),
                "alerts": {
                    s: self._slo_alert_family.labels(objective=k,
                                                     state=s)
                    for s in ("warning", "burning")},
            }


class _Active:
    """Host view of one occupied slot. ``suppress`` is the replay
    offset: tokens up to that count were already streamed before a
    fault and are re-derived silently. ``tokens``/``logprobs`` hold the
    CLIENT-VISIBLE stream — tokens held back by the stop matcher (a
    possible stop-sequence prefix) live inside ``matcher`` until
    flushed or trimmed; replay re-derives them for free."""

    __slots__ = ("request", "tokens", "logprobs", "first_token_time",
                 "suppress", "matcher")

    def __init__(self, request: Request):
        self.request = request
        self.tokens: List[int] = []
        self.logprobs: List[float] = []
        self.first_token_time: Optional[float] = None
        self.suppress = 0
        self.matcher = (StopMatcher(request.stop)
                        if request.stop else None)


class _ReplayState:
    """Recovery bookkeeping for one request across rebuilds: the
    tokens already streamed (the 'last known-good snapshot' replay
    re-derives), retry attempts consumed, and the backoff gate."""

    __slots__ = ("tokens", "logprobs", "attempts", "not_before")

    def __init__(self):
        self.tokens: List[int] = []
        self.logprobs: List[float] = []
        self.attempts = 0
        self.not_before = float("-inf")


class _Parked:
    """One paused conversation in the host-swap tier: the live
    :class:`_Active` it continues as on a swap-resume (stream state,
    stop matcher, held tokens — all intact), plus park metadata.
    ``swap`` flips False when the tier capacity-evicts the payload;
    the conversation then resumes by recompute from the grow-only
    emitted-prefix snapshot the park took first."""

    __slots__ = ("act", "n_pages", "swap", "parked_at")

    def __init__(self, act: _Active, n_pages: int, swap: bool,
                 parked_at: float):
        self.act = act
        self.n_pages = n_pages
        self.swap = swap
        self.parked_at = parked_at


#: _ingest outcomes: the slot is still decoding, was released, or a
#: retire-seam fault triggered recovery mid-call (the caller must
#: abandon its unpack/admission loop — scheduler state was rebuilt)
_LIVE, _RELEASED, _RECOVERED = 0, 1, 2


@dataclasses.dataclass
class EvictedRequest:
    """One interrupted request handed to a :attr:`Scheduler.on_evict`
    hook instead of an ``error`` completion: the request itself plus
    the longest CLIENT-VISIBLE stream it was sent (the grow-only
    emitted-prefix snapshot fault replay maintains). A fleet router
    resubmits it to a healthy replica with
    ``submit(request, replay_prefix=tokens)`` — replay re-derives the
    prefix silently, so the client stream continues bit-identical with
    zero duplicate or lost tokens."""

    request: Request
    tokens: List[int]
    logprobs: List[float]


class Scheduler:
    """Drive an :class:`Engine` over a stream of requests.

    >>> sched = Scheduler(engine, pipeline_depth=2)
    >>> sched.submit(Request("r0", prompt, max_tokens=16))
    >>> sched.run_until_idle()
    >>> sched.completions["r0"].tokens

    ``clock`` is injectable (tests drive deadlines with a fake clock);
    it must be monotonic — inject ``sleep`` alongside it (backoff
    waits go through ``sleep``, and real sleeping cannot advance a
    fake clock). ``metrics`` receives one record per step plus one per
    completion. ``pipeline_depth`` >= 2 overlaps host work with device
    decode (see module docstring); ``max_admit_batch`` caps how many
    queued requests one tick hands to ``Engine.admit_many`` (None =
    all that fit the free slots; 1 = serial single admits, the A/B
    baseline). ``resilience`` tunes recovery/overload policy
    (defaults: :class:`~apex_tpu.serving.resilience.ResilienceConfig`).

    Self-tuning (``tuner=TunerConfig(...)``,
    :mod:`apex_tpu.serving.tuner`): a scheduler-owned controller tunes
    the declared knob ladders — ``decode_chunk`` / ``pipeline_depth``
    / ``max_admit_batch`` / ``spec_k`` — online from per-chunk
    tokens-per-second EWMAs, switching ONLY among pre-warmed compiled
    variants (``EngineConfig.decode_chunks`` / ``spec_ks``; validated
    at construction) so an armed recompile guard stays flat. One knob
    moves per probe window (coordinate descent), probes serialize to
    one in-flight chunk, and the controller hard-freezes to the base
    operating point during constrained decoding, fault replay,
    rebuilds, and drain. ``pipeline_depth`` and ``max_admit_batch``
    become LIVE attributes under a tuner (the controller rewrites them
    per tick); a tuner owning ``spec_k`` replaces the spec gate. Every
    decision and every observation it derives from is a
    flight-recorder event, so a tuning trajectory replays
    bit-identically from a post-mortem bundle. Token streams stay
    bit-identical to any fixed-knob run (the chunk-parity and
    pipelined==serial oracles extend across controller switching).

    Black box (``apex_tpu.telemetry.flightrec``): pass ``recorder`` (a
    :class:`~apex_tpu.telemetry.flightrec.FlightRecorder`) to log every
    load-bearing decision as O(1) event appends, and ``bundle_dir`` to
    auto-dump a self-contained post-mortem bundle on any fault
    detection, guard alarm, watchdog trip, or terminal failure
    (:meth:`dump_bundle` triggers one on demand;
    ``python -m apex_tpu.telemetry.replay <bundle>`` re-runs it and
    checks the replayed streams bit-identical). Per-request replay
    records (prompt/sampling/emitted prefix) are kept regardless —
    live requests exactly, completed ones in a ``request_log``-bounded
    ring.
    """

    def __init__(self, engine: Engine, *, max_queue: int = 256,
                 metrics: Optional[profiler.MetricsLogger] = None,
                 registry=None, spans=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 pipeline_depth: int = 1,
                 max_admit_batch: Optional[int] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 spec_gate: Optional[SpecGateConfig] = None,
                 tuner: Optional[TunerConfig] = None,
                 tenancy: Optional[TenancyConfig] = None,
                 slo: Optional[SLOConfig] = None,
                 recorder=None, bundle_dir: Optional[str] = None,
                 bundle_meta: Optional[Dict] = None,
                 max_auto_bundles: int = 4,
                 request_log: int = 4096,
                 preempt: Optional[bool] = None,
                 on_evict: Optional[
                     Callable[[List[EvictedRequest], str], None]] = None,
                 journal=None):
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth {pipeline_depth} must be >= 1 (1 = the "
                f"serial loop)")
        if max_admit_batch is not None and max_admit_batch < 1:
            raise ValueError(
                f"max_admit_batch {max_admit_batch} must be >= 1 or None")
        self.engine = engine
        self.max_queue = max_queue
        self.metrics = metrics
        self.clock = clock
        self.sleep = sleep
        self.pipeline_depth = pipeline_depth
        self.max_admit_batch = max_admit_batch
        #: constructor values, kept verbatim for the bundle config: a
        #: tuner rewrites the live attributes per tick (a mid-probe
        #: dump would otherwise record a transient candidate as "the"
        #: config and skew replay's rebuilt controller base)
        self._cfg_pipeline_depth = pipeline_depth
        self._cfg_max_admit_batch = max_admit_batch
        self.resilience = resilience or ResilienceConfig()
        #: multi-tenant policy (serving.tenancy): per-tenant
        #: weighted-fair queueing with deficit counters + priority
        #: aging (engaged whenever more than one tenant is backlogged
        #: — a single-tenant queue pops strict FIFO, bit-identical to
        #: the pre-tenancy scheduler), token-budget rate limits
        #: (submit raises TenantThrottled → the API's 429 +
        #: Retry-After), and per-tenant accounting. The book exists
        #: even without a TenancyConfig so tenant-labeled telemetry
        #: and summaries always work; rates require a config.
        self._tenancy_cfg = tenancy
        self.tenants = TenantBook(tenancy, clock)
        self._throttled = 0
        #: telemetry sinks (both optional): a telemetry.Registry the
        #: scheduler counts/observes into, and a telemetry.SpanRecorder
        #: receiving per-request phase marks + dispatch sections. The
        #: recorder's clock is slaved to the scheduler's so injected
        #: test clocks produce deterministic timelines.
        self.telemetry = (None if registry is None
                          else _RegistryMetrics(registry, engine))
        self.spans = spans
        if spans is not None:
            spans.clock = self.clock
        self._registry = registry
        #: flight recorder (telemetry.flightrec.FlightRecorder) — the
        #: always-on black box: every load-bearing host decision is one
        #: O(1) event append. Its clock is slaved to the scheduler's,
        #: like the span recorder's, so injected test clocks produce
        #: deterministic timelines; fault-plan injections are observed
        #: through FaultPlan.on_inject so a bundle shows injections
        #: next to detections.
        self.recorder = recorder
        if recorder is not None:
            recorder.clock = self.clock
        if engine.fault_plan is not None:
            # the NEWEST scheduler owns the observer either way: a
            # recorder-less scheduler over a shared engine (the bench's
            # on/off A/B, a service rebuilding on config reload) must
            # clear a dead predecessor's wiring, not inherit it
            engine.fault_plan.on_inject = (
                None if recorder is None else
                lambda spec: recorder.record(
                    "inject", spec.point, spec.index, spec.kind))
        #: post-mortem bundles: ``bundle_dir`` is where auto-dumps land
        #: (fault detection / watchdog trip / guard alarm / terminal
        #: failure — at most ``max_auto_bundles``, one per trigger
        #: wave; None disables auto-dump, :meth:`dump_bundle` with an
        #: explicit dir still works). ``bundle_meta`` is carried
        #: verbatim into the manifest — put params provenance there
        #: (``{"params": {"init_seed": 0}}``) so
        #: ``python -m apex_tpu.telemetry.replay`` can rebuild the
        #: model.
        self.bundle_dir = bundle_dir
        self.bundle_meta = dict(bundle_meta or {})
        self.max_auto_bundles = max_auto_bundles
        #: bundle paths written so far (auto + manual), oldest first
        self.bundles_written: List[str] = []
        self._auto_bundles = 0
        self._bundle_counter = 0
        self._dump_token = 0        # one auto-dump per trigger wave
        self._last_dump_token = -1
        #: replayable per-request records — live (queued/active) by id,
        #: completed in a bounded ring; the bundle's requests.jsonl
        self._req_records: Dict[str, Dict] = {}
        self._req_done = Ring(request_log)
        self._submit_seq = 0
        #: router-facing eviction hook (``(evicted, cause) -> None``):
        #: when set, work this scheduler can no longer serve — every
        #: queued/active request at terminal failure, or a single
        #: request whose bounded retries exhausted — is handed over as
        #: :class:`EvictedRequest` records (emitted prefix attached)
        #: INSTEAD of being aborted with ``error`` events, so a fleet
        #: router can fail it over to a healthy replica with the client
        #: stream intact. None (the default) keeps the single-engine
        #: abort-with-error semantics unchanged.
        self.on_evict = on_evict
        #: durable write-ahead request journal
        #: (:class:`apex_tpu.serving.journal.Journal`): every
        #: durable-relevant host decision — submits, emitted-prefix
        #: extends at fetch boundaries, finishes, park/resume,
        #: registrations — is appended so
        #: :func:`~apex_tpu.serving.journal.recover_scheduler` can
        #: continue every unfinished stream bit-identically after a
        #: process death. None (the default) journals nothing and
        #: leaves the hot path untouched.
        self.journal = journal
        #: per-request journaled stream length — the extend cursor
        self._journal_len: Dict[str, int] = {}
        self._journal_recovered = 0
        #: last journal counters mirrored into the registry (the
        #: commit refreshes deltas, so shared registries never
        #: double-count)
        self._j_seen = {"appends": 0, "rotations": 0,
                        "compactions": 0, "fsync_s": 0.0}
        if journal is not None and journal.seq == 0:
            # a FRESH journal opens with the engine spec (describe()
            # round-trip) so recovery can refuse an incompatible
            # engine_factory; a recovered journal keeps its meta
            self._jlog("meta", format=journal_mod.FORMAT_VERSION,
                       engine_spec=journal_mod._engine_spec(engine))
        self._gate_state_seen: Optional[float] = None
        #: the ok → degraded → draining → failed state machine; wire
        #: ``MetricsServer(health=sched.health.healthz)`` to serve it
        self.health = HealthMonitor(
            registry=registry,
            recovery_chunks=self.resilience.recovery_chunks,
            on_transition=self._on_health_transition)
        self.queue: Deque[Request] = collections.deque()
        self.active: Dict[int, _Active] = {}
        self.completions: Dict[str, Completion] = {}
        self.events: Deque[StreamEvent] = collections.deque()
        self.ttft_stats = profiler.LatencyStats()
        self.token_latency_stats = profiler.LatencyStats()
        self._free: List[int] = self._reset_free()
        #: chunks dispatched but not yet fetched, oldest first; each
        #: entry is (handle, slot->_Active snapshot at dispatch,
        #: dispatch time, pipeline depth at dispatch incl. this chunk,
        #: tuner operating point at dispatch — None without a tuner)
        self._inflight: Deque[
            Tuple[StepHandle, Dict[int, _Active], float, int,
                  Optional[Dict[str, int]]]] = collections.deque()
        #: recovery bookkeeping per interrupted request (cleared at
        #: completion)
        self._replay: Dict[str, _ReplayState] = {}
        #: prefix-pool hits keyed by request_id — resolved ONCE at
        #: submit (match_prefix is pure host work) and reused at every
        #: (re-)admission, so fault replay rides the same (page, split)
        #: and stays bit-identical
        self._prefix_hits: Dict[str, Tuple[int, int]] = {}
        self._prefix_hit_count = 0
        self._prefix_miss_count = 0
        #: the in-flight chunked-prefill admission (one at a time —
        #: the engine's scratch holds one prompt): (progress, request).
        #: Each tick advances it ONE chunk forward before the decode
        #: dispatch, so a long prompt's ingestion interleaves with
        #: everyone else's decode waves instead of stalling them.
        #: ``_chunked_fresh`` marks the start tick — chunk 0 was this
        #: tick's one chunk dispatch, so _advance_chunked must not add
        #: a second
        self._chunked: Optional[Tuple[ChunkedAdmission, Request]] = None
        self._chunked_fresh = False
        self._chunked_admissions = 0
        self._chunked_chunks = 0
        self._page_share_hits = 0
        self._pages_exhausted_waits = 0
        #: host-swap oversubscription (EngineConfig.host_swap): paused
        #: conversations by request id (their _Active intact for a
        #: swap-resume) and the FIFO of ids queued for resumption —
        #: drained BEFORE admissions each tick, so a resuming client
        #: mid-stream never waits behind new arrivals. ``preempt``
        #: (default: on whenever the engine has a host tier) lets page
        #: pressure evict the WFQ-furthest-ahead tenant's pages; the
        #: victim replays bit-identically through the fault machinery.
        if preempt and not engine.host_swap_enabled:
            raise ValueError(
                "preempt=True needs EngineConfig.host_swap — without "
                "the emitted-prefix replay contract the host tier "
                "anchors, an evicted stream could not continue")
        self.preempt = (engine.host_swap_enabled if preempt is None
                        else bool(preempt))
        self._parked: Dict[str, _Parked] = {}
        self._resume_q: Deque[str] = collections.deque()
        self._pauses = 0
        self._preemptions = 0
        self._swap_resumes = 0
        self._recompute_resumes = 0
        self._swap_capacity_drops = 0
        self._steps = 0
        self._tokens_emitted = 0
        self._admitted_requests = 0
        self._admit_dispatches = 0
        self._retries = 0
        self._retry_exhausted = 0
        self._rebuilds = 0
        self._shed = 0
        self._watchdog_trips = 0
        self._evicted_requests = 0
        self._consecutive_rebuilds = 0
        #: EWMA of chunk dispatch→fetch wall time — the overload
        #: estimator behind deadline shedding and the QueueFull
        #: retry-after hint
        self._chunk_ewma = 0.0
        #: self-tuning control plane (serving.tuner): a Controller over
        #: the declared knob ladders, switching ONLY among pre-warmed
        #: compiled variants (validated against the engine's resolved
        #: ladders right here, so a bad ladder fails at construction,
        #: not as a mid-serve recompile). When it owns the ``spec_k``
        #: knob it REPLACES the spec gate — one controller per knob.
        tunes_spec = tuner is not None and tuner.spec_k is not None
        self._tuner: Optional[Controller] = None
        if tuner is not None:
            self._tuner = self._build_tuner(tuner, engine)
        #: speculative-decoding payoff gate (None unless the engine
        #: carries a spec_k > 0 base variant and the tuner does not own
        #: the knob): decides per dispatch which pre-warmed chunk
        #: variant to run — see SpecGateConfig
        if engine.engine_cfg.spec_k > 0 and not tunes_spec:
            self._gate: Optional[_SpecGate] = _SpecGate(
                spec_gate or SpecGateConfig(), engine.engine_cfg.spec_k)
        else:
            if spec_gate is not None:
                raise ValueError(
                    "spec_gate given but unusable — speculation needs "
                    "EngineConfig.spec_k > 0, and a tuner that owns "
                    "the spec_k knob replaces the gate (two "
                    "controllers would fight over one variant choice)")
            self._gate = None
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_chunks = 0
        self._alarms_seen = self._guard_alarm_count()
        self._started: Optional[float] = None
        #: SLO observatory (telemetry.slo): streaming quantile sketches
        #: over the four latency surfaces this scheduler already
        #: timestamps (ttft / token_latency / queue_wait / e2e, global
        #: + per-tenant), plus one burn-rate machine per declared
        #: objective. The monitor shares the scheduler clock and
        #: recorder, so its evaluation inputs and every state
        #: transition land in bundles and replay bit-identically
        #: (telemetry.replay.replay_slo). None = no sketches, summary()
        #: unchanged.
        self._slo_cfg = slo
        self.slo: Optional[SLOMonitor] = None
        if slo is not None:
            self.slo = SLOMonitor(slo, clock=self.clock,
                                  recorder=recorder,
                                  on_state=self._on_slo_state)
            if self.telemetry is not None:
                self.telemetry.bind_slo(
                    SLO_METRICS, [o.key() for o in slo.objectives])
        # steady-decode split: wall time attributable to decode chunks
        # (dispatch-to-fetch, overlap-deduplicated so pipelined chunks
        # never double-count an interval) and the tokens they emitted —
        # TTFT (admission/prefill) excluded, so summary() can report
        # the two regimes separately
        self._decode_time = 0.0
        self._decode_tokens = 0
        self._decode_mark = float("-inf")

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request, *,
               replay_prefix: Optional[Sequence[int]] = None,
               replay_logprobs: Optional[Sequence[float]] = None) -> None:
        """Enqueue ``request``; raises :class:`QueueFull` at capacity
        (with queue depth + a retry-after hint attached) and
        :class:`~apex_tpu.serving.resilience.EngineFailed` once the
        health machine is terminal. Prompt-validity errors raise
        immediately; a prompt that already ends in the request's eos
        token completes here with zero generated tokens.

        ``replay_prefix`` (router-facing) primes the grow-only
        emitted-prefix snapshot with tokens the client ALREADY saw on
        another replica before a failover: generation re-derives them
        from the prompt and suppresses the duplicate events, exactly
        like local fault replay, so the continued stream is
        bit-identical."""
        if self.health.state == HEALTH_FAILED:
            raise EngineFailed(
                f"engine health is failed ({self.health.last_cause}); "
                f"not accepting requests")
        if request.request_id in self.completions or any(
                a.request.request_id == request.request_id
                for a in self.active.values()) or any(
                r.request_id == request.request_id for r in self.queue) \
                or request.request_id in self._parked:
            raise ValueError(f"duplicate request_id {request.request_id!r}")
        request.sampling.validate()
        prompt = list(request.prompt)
        ecfg = self.engine.engine_cfg
        # the slot must fit prompt + at least one generated token
        limit = min(ecfg.max_prompt_len, ecfg.max_seq_len - 1)
        if not 1 <= len(prompt) <= limit:
            raise ValueError(
                f"prompt length {len(prompt)} outside [1, {limit}]")
        room = ecfg.max_seq_len - len(prompt)
        if not 1 <= request.max_tokens <= room:
            raise ValueError(
                f"max_tokens {request.max_tokens} outside [1, {room}] "
                f"for a {len(prompt)}-token prompt at max_seq_len "
                f"{ecfg.max_seq_len} — a clamped budget would silently "
                f"break solo-generate parity")
        eos = request.eos_token_id
        if eos is not None and not 0 <= eos < self.engine.cfg.vocab_size:
            raise ValueError(
                f"eos_token_id {eos} outside vocab "
                f"[0, {self.engine.cfg.vocab_size})")
        if request.stop:
            for s in request.stop:
                if not len(s):
                    raise ValueError(
                        "stop sequences must be non-empty token lists")
        if request.constraint is not None \
                and ecfg.decode_chunk != 1:
            raise ValueError(
                f"schema-constrained requests need decode_chunk == 1 "
                f"(the vocab mask advances host-side between "
                f"dispatches; a {ecfg.decode_chunk}-token chunk would "
                f"apply a stale mask), got decode_chunk="
                f"{ecfg.decode_chunk}")
        if not request.tenant:
            request.tenant = DEFAULT_TENANT
        if request.adapter:
            # validated HERE, not at admission: a bad adapter id that
            # only surfaced mid-serve would be quarantined as a fault
            if not self.engine.adapter_pool_enabled:
                raise ValueError(
                    f"request carries adapter {request.adapter} but "
                    f"the engine's adapter pool is disabled "
                    f"(EngineConfig.adapter_slots == 0)")
            n_reg = self.engine.adapters_registered
            if not 1 <= request.adapter <= n_reg:
                raise ValueError(
                    f"adapter {request.adapter} outside the "
                    f"registered ids [1, {n_reg}] (0 is the pinned "
                    f"base adapter; Engine.register_adapter issues "
                    f"the rest)")
        now = self.clock()
        request.arrival_time = now
        self._dump_token += 1
        rec = self.recorder
        book = self.tenants
        # bounded tenant cardinality: unauthenticated per-request
        # identities fold into the overflow tenant past max_tenants
        # (the request is REWRITTEN so every downstream consumer —
        # WFQ, buckets, metrics, bundle records — sees one identity)
        tenant = request.tenant = book.admit_tenant(request.tenant)
        if (request.eos_token_id is not None
                and prompt[-1] == request.eos_token_id):
            book.stats(tenant).submitted += 1
            if self.telemetry is not None:
                self.telemetry.submitted.inc()
            self._record_request(request, now)
            if rec is not None:
                rec.record("submit_terminal", request.request_id)
            self._complete(request, [], FINISH_EOS, ttft=None, now=now)
            return
        plan = self.engine.fault_plan
        spec = plan.take("submit") if plan is not None else None
        flooded = spec is not None and spec.kind == KIND_FLOOD
        if flooded or len(self.queue) >= self.max_queue:
            depth = self.max_queue if flooded else len(self.queue)
            hint = depth * self._chunk_ewma
            self._shed += 1
            if rec is not None:
                rec.record("queue_full", request.request_id, depth,
                           flooded)
            self.health.record_fault("queue_full")
            self._maybe_dump("queue_full")
            book.stats(tenant).shed += 1
            if self.telemetry is not None:
                self.telemetry.shed["queue_full"].inc()
                self.telemetry.tenant(tenant)["shed"][
                    "queue_full"].inc()
            raise QueueFull(
                f"queue at capacity ({depth}"
                f"{', injected flood' if flooded else ''}); retry in "
                f"~{hint:.3f}s", queue_depth=depth, retry_after_s=hint)
        # per-tenant token-budget rate limit — checked AFTER the
        # queue-capacity gate so a QueueFull rejection never debits
        # the bucket (the request served nothing; charging it would
        # starve a well-behaved tenant through repeated flood
        # rejections), and SKIPPED for failover hand-offs
        # (replay_prefix: the original submit already charged this
        # request's budget — a second charge on re-placement would
        # double-bill the tenant and could crash the router loop with
        # an un-routable throttle). Other tenants' streams are
        # untouched either way (the zero-drift contract); the
        # rejection carries the bucket refill time as Retry-After.
        if replay_prefix is None:
            wait = book.throttle(tenant, request.max_tokens, now)
            if wait is not None:
                self._throttled += 1
                book.stats(tenant).throttled += 1
                book.stats(tenant).shed += 1
                if rec is not None:
                    rec.record("tenant_throttle", request.request_id,
                               tenant, wait)
                if self.telemetry is not None:
                    self.telemetry.shed["tenant_rate"].inc()
                    self.telemetry.tenant(tenant)["shed"][
                        "tenant_rate"].inc()
                raise TenantThrottled(
                    f"tenant {tenant!r} over its token budget; retry "
                    f"in ~{wait:.3f}s", tenant=tenant,
                    retry_after_s=wait)
        if self.engine.prefix_pool_enabled and not request.adapter:
            # adapter-carrying requests never match the prefix pool:
            # pooled prefixes hold BASE-weight K/V, and a hit would
            # decode against cache bytes a cold adapter prefill would
            # not produce (the engine rejects the combination too)
            hit = self.engine.match_prefix(prompt)
            if hit is not None:
                self._prefix_hits[request.request_id] = hit
                self._prefix_hit_count += 1
            else:
                self._prefix_miss_count += 1
            if self.telemetry is not None:
                (self.telemetry.prefix_hits if hit is not None
                 else self.telemetry.prefix_misses).inc()
        if self.engine.paged:
            # a request that could NEVER fit the pool (even with every
            # other slot free) would wait at the queue head forever —
            # reject loudly at submit instead; transient exhaustion is
            # the normal backpressure path. The need is the PRIVATE
            # footprint — a prefix hit's shared pages are pinned, not
            # allocated (checked AFTER match_prefix so a CoW-discounted
            # request that fits is never falsely rejected)
            needed = self._request_pages_needed(request)
            if needed > self.engine.page_allocator.capacity:
                raise ValueError(
                    f"request needs {needed} pages but the pool only "
                    f"has {self.engine.page_allocator.capacity} — "
                    f"raise EngineConfig.num_pages or shrink the "
                    f"request")
        self._record_request(request, now)
        self._journal_submit(request, now)
        if replay_prefix:
            # failover hand-off: everything another replica streamed
            # becomes this scheduler's last-known-good snapshot — the
            # same grow-only record a local fault replay maintains
            st = self._replay.setdefault(request.request_id,
                                         _ReplayState())
            if len(replay_prefix) > len(st.tokens):
                st.tokens = [int(t) for t in replay_prefix]
                st.logprobs = list(replay_logprobs or [])
            # journaled AND committed immediately (not buffered until
            # the next fetch boundary): the hand-off prefix is the
            # client's already-seen stream — a crash before the first
            # chunk must not forget it, so it gets durability to the
            # fsync policy's level right here (batch/always fsync,
            # none flushes to the page cache)
            self._journal_extend(request.request_id, st.tokens,
                                 st.logprobs)
            if self.journal is not None:
                self.journal.commit()
        # a tenant (re-)entering the backlog competes from "now": its
        # deficit counter clamps up to the minimum among the tenants
        # currently holding queued/active work — idle time is not
        # banked credit (the backlog set is computed BEFORE this
        # request joins it; submit already walks the queue for the
        # duplicate-id check, so this adds no new asymptotics)
        backlogged = {a.request.tenant for a in self.active.values()}
        backlogged.update(r.tenant for r in self.queue)
        if tenant not in backlogged:
            book.rejoin(tenant, min(
                (book.service_of(t) for t in backlogged),
                default=book.service_of(tenant)))
        self.queue.append(request)
        book.stats(tenant).submitted += 1
        book.note_backlogged(tenant)
        if rec is not None:
            rec.record("submit", request.request_id, len(prompt),
                       request.max_tokens, len(self.queue))
        if self.telemetry is not None:
            self.telemetry.submitted.inc()
            self.telemetry.queue_depth.set(len(self.queue))
        if self.spans is not None:
            self.spans.mark(request.request_id, spans_mod.PHASE_QUEUED)

    # -- the loop ----------------------------------------------------------

    def step(self) -> None:
        """One scheduler tick: expire/shed deadlines, batch-admit
        queued requests into free slots, dispatch the next decode chunk
        if any slot is live, then fetch + unpack chunks down to the
        pipeline depth (ALL of them when nothing was dispatched — the
        drain path, so a tick always makes progress). At depth 1 this
        is the serial loop: dispatch, fetch, unpack. Deadlines and
        admissions are checked between chunks — the ``decode_chunk``
        admission-latency/throughput tradeoff, now also the
        pipeline-depth one. A fault detected anywhere in the tick
        triggers quarantine + rebuild + replay instead of escaping
        (see module docstring); once the health machine is terminal
        the tick is a no-op."""
        self._dump_token += 1
        if self.health.state == HEALTH_FAILED:
            return
        now = self.clock()
        if self._started is None:
            self._started = now
        self._poll_guard_alarms()
        self._sync_tuner()
        self._sync_slo(now)
        self._expire(now)
        # admissions FIRST, then one chunk of any in-progress chunked
        # prefill, then the decode dispatch: a short prompt's
        # admission never queues behind this tick's chunk forward, so
        # the long admission inflates nobody's TTFT — the interleave
        # that keeps a 32k-token admission from stalling every other
        # stream
        self._admit_queued(now)
        self._advance_chunked(now)
        dispatched = bool(self.active) and self._dispatch_chunk()
        keep = self.pipeline_depth - 1 if dispatched else 0
        while len(self._inflight) > keep:
            self._collect_oldest()
        self._steps += 1
        if self.telemetry is not None:
            self.telemetry.steps.inc()
            self.telemetry.queue_depth.set(len(self.queue))
            self.telemetry.active_slots.set(len(self.active))
            if len(self.tenants._stats) > 1:
                # per-tenant depth gauges only once a SECOND tenant
                # exists — the universal single-tenant case must not
                # pay an extra O(queue) walk per tick
                depth: Dict[str, int] = {}
                for r in self.queue:
                    depth[r.tenant] = depth.get(r.tenant, 0) + 1
                for t in self.tenants._stats:
                    self.telemetry.tenant(t)["queue"].set(
                        depth.get(t, 0))
            if self.engine.paged:
                ps = self.engine.page_stats()
                self.telemetry.pages_in_use.set(ps["pages_in_use"])
                self.telemetry.pages_free.set(ps["pages_free"])
                self.telemetry.pages_shared.set(ps["pages_shared"])
                self.telemetry.page_fragmentation.set(
                    ps["fragmentation"])
                self.telemetry.pages_swapped.set(ps["pages_swapped"])
                self.telemetry.swap_bytes.set(ps["swap_bytes"])
        if self.metrics is not None:
            elapsed = max(self.clock() - self._started, 1e-9)
            self.metrics.log(self._steps, {
                "queue_depth": len(self.queue),
                "slot_occupancy": len(self.active) / self.engine.slots,
                "tokens_emitted": self._tokens_emitted,
                "tokens_per_sec": self._tokens_emitted / elapsed,
            })

    def drain(self) -> None:
        """Fetch + unpack every in-flight chunk (pipeline drain): after
        this, ``events``/``completions`` reflect all dispatched work.
        The health machine reads ``draining`` for the duration (a live
        ``/healthz`` probe answers 503 — stop routing traffic here),
        then returns to its prior state."""
        if self._tuner is not None:
            # drained chunks are shutdown traffic, not steady state —
            # the controller must neither measure nor steer on them
            # (it thaws at the next live tick's _sync_tuner)
            self._tuner.freeze("drain")
        self.health.begin_drain()
        try:
            while self._inflight:
                self._collect_oldest()
        finally:
            self.health.end_drain()

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        """Step until queue, slots, and the pipeline are empty (offline
        batch mode). When every queued request is gated on retry
        backoff and nothing is in flight, waits out the earliest gate
        via ``sleep`` instead of spinning."""
        steps = 0
        while (self.queue or self.active or self._inflight
               or self._chunked is not None or self._resume_q):
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"not idle after {max_steps} steps — live slots "
                    f"{sorted(self.active)}, queue {len(self.queue)}, "
                    f"{len(self._inflight)} chunks in flight")
            wait = self._backoff_wait_s()
            if wait is not None:
                self.sleep(wait)

    def pop_events(self) -> List[StreamEvent]:
        """Drain the response stream."""
        out = list(self.events)
        self.events.clear()
        return out

    def idle(self) -> bool:
        """True when there is nothing to do — queue, slots, pipeline,
        any chunked admission, and the resume queue are all empty (the
        API driver thread sleeps instead of spinning ticks). Parked
        conversations do NOT count: they wait for an explicit
        :meth:`resume`."""
        return not (self.queue or self.active or self._inflight
                    or self._chunked is not None or self._resume_q)

    def overload_hint_s(self) -> float:
        """The queue-drain estimate behind :class:`QueueFull`'s
        ``retry_after_s`` (depth × measured chunk latency), exposed so
        an ingress layer can pre-flight an all-or-nothing batch (an
        ``n>1`` fan must not half-land) with the same hint a rejection
        would carry."""
        return len(self.queue) * self._chunk_ewma

    def can_accept(self, n: int = 1) -> bool:
        """Whether ``n`` more submissions fit the queue right now —
        the all-or-nothing pre-flight the API front end (and the fleet
        router, which aggregates it across replicas) checks before
        fanning a batch that must not half-land. Capacity only:
        terminal health surfaces as :class:`EngineFailed` from
        :meth:`submit` (a 503, not a 429)."""
        return len(self.queue) + n <= self.max_queue

    def register_adapter(self, weights=None, *,
                         name: Optional[str] = None,
                         seed: Optional[int] = None) -> int:
        """Register a LoRA adapter into the engine's pool
        (:meth:`Engine.register_adapter`) and log the
        ``adapter_register`` flight-recorder event — the scheduler is
        the recorder's owner, so registration evidence lands in
        post-mortem bundles next to the admissions that used it."""
        aid = self.engine.register_adapter(weights, name=name,
                                           seed=seed)
        meta = self.engine._adapter_meta.get(aid, {})
        if self.recorder is not None:
            self.recorder.record("adapter_register",
                                 meta.get("name"), aid,
                                 meta.get("seed"))
        # journaled with its derivation seed: recovery re-registers by
        # name (idempotent) and re-derives the exact weights; an
        # explicit-weights registration journals seed=None and its
        # requests are skipped at recovery (counted, never guessed)
        self._jlog("adapter", name=meta.get("name"),
                   seed=meta.get("seed"), rank=meta.get("rank"),
                   adapter_id=aid)
        return aid

    def register_prefix(self, tokens) -> int:
        """Register a shared prompt-prefix template into the engine's
        pool (:meth:`Engine.register_prefix`) and journal the token
        list, so a crash-recovered scheduler repopulates the pool and
        replayed admissions ride the same (page, split) hits."""
        page = self.engine.register_prefix(tokens)
        self._jlog("prefix", tokens=[int(t) for t in tokens])
        return page

    def tenant_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant accounting: weight, submitted/admitted/shed/
        throttled counts, served tokens, and the live WFQ deficit
        counter (:meth:`apex_tpu.serving.tenancy.TenantBook.summary`)."""
        return self.tenants.summary()

    # -- host-swap oversubscription (EngineConfig.host_swap) ----------------

    def pause(self, request_id: str) -> bool:
        """Park an ACTIVE request's conversation in the host-RAM swap
        tier (:meth:`Engine.park_slot`): its private HBM pages swap
        out, the slot frees for other traffic, and the stream
        continues bit-identically after :meth:`resume` — held stop-
        matcher tokens, PRNG state, everything. Never mid-chunk: every
        in-flight chunk is collected first (the dispatched tables
        still map the pages being freed). Returns False when the
        request is not active by then — it finished in a collected
        chunk, is still queued, or was already parked."""
        if not self.engine.host_swap_enabled:
            raise ValueError(
                "pause() needs EngineConfig.host_swap — the engine "
                "has no host tier to park into")
        while self._inflight:
            self._collect_oldest()
        for slot, act in sorted(self.active.items()):
            if act.request.request_id == request_id:
                self._park(slot, act, self.clock())
                return True
        return False

    def resume(self, request_id: str) -> bool:
        """Queue a parked conversation for resumption — drained BEFORE
        admissions each tick, and attempted immediately here when a
        slot is free. ``EngineConfig.resume_policy`` prices the path
        per conversation: ``swap`` scatters the parked payload back
        and the SAME stream object continues; ``recompute`` drops the
        payload and re-derives the emitted prefix through fault
        replay; ``auto`` compares the measured swap-in EWMA against
        replay's (emitted tokens × chunk-latency EWMA) and takes the
        cheaper one. Returns False for an id that is not parked."""
        if request_id not in self._parked:
            return False
        if request_id not in self._resume_q:
            self._resume_q.append(request_id)
        self._admit_parked(self.clock())
        return True

    @property
    def parked_requests(self) -> List[str]:
        """Ids of paused conversations, oldest park first."""
        return sorted(self._parked,
                      key=lambda rid: self._parked[rid].parked_at)

    def _park(self, slot: int, act: _Active, now: float) -> None:
        """Move one active slot into the host tier: grow the replay
        snapshot FIRST (the recompute fallback — and the bundle's
        record of what the client saw), swap the pages out, free the
        slot. An engine-seam failure recovers like any other fault
        (the conversation replays from the snapshot just taken)."""
        rid = act.request.request_id
        st = self._replay.setdefault(rid, _ReplayState())
        if len(act.tokens) > len(st.tokens):
            st.tokens = list(act.tokens)
            st.logprobs = list(act.logprobs)
        n_pages = self.engine.slot_page_count(slot)
        try:
            evicted = self.engine.park_slot(slot, rid)
        except Exception as e:  # park rides the retire seam
            self._recover(now, cause="retire", detail=str(e),
                          affected=[])
            return
        self.active.pop(slot)
        self._free.append(slot)
        self._pauses += 1
        self._parked[rid] = _Parked(act, n_pages,
                                    self.engine.host_parked(rid), now)
        for ek in evicted:
            # capacity eviction only drops swap payloads — those
            # conversations (possibly including this one) downgrade
            # to recompute-resume; nothing is lost
            pk = self._parked.get(ek)
            if pk is not None and pk.swap:
                pk.swap = False
                self._swap_capacity_drops += 1
        # the snapshot just grown is the recompute-resume contract —
        # journal it now plus the park marker, so a crash while parked
        # recovers the conversation instead of forgetting it
        self._journal_extend(rid, st.tokens, st.logprobs)
        self._jlog("park", request_id=rid)
        if self.recorder is not None:
            self.recorder.record("page_swap_out", rid, slot, n_pages,
                                 self.engine.parked_bytes(rid))
        if self.spans is not None:
            self.spans.mark(rid, spans_mod.PHASE_QUEUED,
                            note=f"parked ({n_pages} pages)")
        if self.telemetry is not None:
            self.telemetry.active_slots.set(len(self.active))

    def _admit_parked(self, now: float) -> None:
        """Drain the resume queue into free slots. A swap-resume that
        cannot get a slot or pages waits at the queue head — the same
        backpressure admission sees (page pressure may preempt on its
        behalf); a recompute-resume re-enters the request queue's
        FRONT and replays through the fault machinery."""
        while self._resume_q:
            rid = self._resume_q[0]
            pk = self._parked.get(rid)
            if pk is None:      # expired/aborted while queued
                self._resume_q.popleft()
                continue
            act = pk.act
            n_pages = self.engine.parked_pages(rid)
            policy = self.engine.engine_cfg.resume_policy
            use_swap = (pk.swap and self.engine.host_parked(rid)
                        and policy != "recompute")
            if use_swap and policy == "auto":
                cost = self.engine.swap_in_cost_s(n_pages)
                if (cost is not None and self._chunk_ewma > 0.0
                        and cost > len(act.tokens) * self._chunk_ewma):
                    use_swap = False
            if not use_swap:
                # recompute: drop the payload (snapshot was grown at
                # park) and replay from the request queue's front —
                # the resuming client jumps new arrivals
                self._resume_q.popleft()
                self._parked.pop(rid)
                self.engine.drop_parked(rid)
                self._recompute_resumes += 1
                self.queue.appendleft(act.request)
                self._jlog("resume", request_id=rid, path="recompute")
                if self.recorder is not None:
                    self.recorder.record("page_swap_in", rid, -1,
                                         n_pages, "recompute")
                continue
            if not self._free:
                return
            if not self.engine.page_allocator.can_alloc(n_pages):
                self._note_pages_exhausted(act.request, n_pages)
                return
            slot = self._free.pop()
            try:
                self.engine.resume_slot(slot, rid)
            except PagesExhausted as e:
                self._free.append(slot)
                self._note_pages_exhausted(act.request, e.requested)
                return
            except KeyError:
                # capacity-evicted between the check and the take —
                # the next spin takes the recompute branch
                self._free.append(slot)
                pk.swap = False
                continue
            except Exception as e:
                # the scatter donates cache/state: the payload is
                # consumed and the engine poisoned — recover, and
                # replay this conversation from its snapshot alongside
                # every interrupted slot
                self._free.append(slot)
                self._resume_q.popleft()
                self._parked.pop(rid, None)
                self._recover(now, cause="admit", detail=str(e),
                              affected=[], batch_reqs=[act.request])
                return
            self._resume_q.popleft()
            self._parked.pop(rid)
            self.active[slot] = act
            self._swap_resumes += 1
            self._jlog("resume", request_id=rid, path="swap")
            if self.recorder is not None:
                self.recorder.record("page_swap_in", rid, slot,
                                     n_pages, "swap")
            if self.spans is not None:
                self.spans.mark(rid, spans_mod.PHASE_DECODE,
                                note=f"swap-resume slot {slot}")
            if self.telemetry is not None:
                self.telemetry.active_slots.set(len(self.active))

    def _maybe_preempt(self, r: Request, needed: int) -> None:
        """Page pressure meets oversubscription: free the pages of the
        tenant furthest AHEAD of its WFQ fair share
        (:meth:`~apex_tpu.serving.tenancy.TenantBook.pick_victim`) so
        the starved request admits next tick. Never mid-chunk — every
        in-flight chunk collects first — and never the starved
        request's own lane. The victim replays through the fault
        machinery (snapshot grown here, re-queued at the BACK — it
        yielded its turn); attempts are NOT charged: preemption is a
        scheduling decision, not a fault. Its continued stream is
        bit-identical."""
        if not self.preempt or not self.active:
            return
        while self._inflight:
            self._collect_oldest()
        # collection may have released slots/pages (or recovered a
        # fault) — re-check the pressure before evicting anyone
        if (not self.active
                or self.engine.page_allocator.can_alloc(needed)):
            return
        # only tenants strictly AHEAD of the starved one are fair
        # game: preemption flows one way down the WFQ ordering, so a
        # fresh victim can never preempt its preemptor right back
        # (equal-service tenants fall through to plain backpressure)
        floor = self.tenants.service_of(r.tenant)
        candidates = {
            a.request.tenant: self.tenants.service_of(a.request.tenant)
            for a in self.active.values()
            if self.tenants.service_of(a.request.tenant) > floor}
        if not candidates:
            return
        victim_tenant = self.tenants.pick_victim(candidates)
        victims = sorted(
            (len(a.tokens), slot)
            for slot, a in self.active.items()
            if a.request.tenant == victim_tenant
            and a.request.request_id != r.request_id)
        if not victims:
            return
        _, slot = victims[0]    # least sunk work first
        act = self.active[slot]
        vid = act.request.request_id
        n_pages = self.engine.slot_page_count(slot)
        st = self._replay.setdefault(vid, _ReplayState())
        if len(act.tokens) > len(st.tokens):
            st.tokens = list(act.tokens)
            st.logprobs = list(act.logprobs)
        if self.recorder is not None:
            self.recorder.record(
                "preempt", vid, slot, victim_tenant, n_pages,
                candidates[victim_tenant], dict(sorted(candidates.items())))
        try:
            self.engine.retire(slot)
        except Exception as e:
            self._recover(self.clock(), cause="retire", detail=str(e),
                          affected=[])
            return
        self.engine.free_slot(slot)
        self.active.pop(slot)
        self._free.append(slot)
        self._preemptions += 1
        self.queue.append(act.request)
        if self.spans is not None:
            self.spans.mark(vid, spans_mod.PHASE_QUEUED,
                            note="preempted")
        if self.telemetry is not None:
            self.telemetry.preemptions.inc()
            self.telemetry.queue_depth.set(len(self.queue))
            self.telemetry.active_slots.set(len(self.active))

    @property
    def chunk_latency_ewma_s(self) -> float:
        """The measured decode-chunk latency EWMA (seconds; 0.0 before
        any chunk landed) — the overload estimator behind deadline
        shedding and retry-after hints, exposed so a fleet router can
        weight replicas by how fast they actually serve."""
        return self._chunk_ewma

    def predicted_ttft_s(self) -> float:
        """What a request submitted NOW would likely see as TTFT on
        this replica: the queue-drain estimate (depth × measured chunk
        latency — :meth:`overload_hint_s`) plus the measured admission
        component — the median gap between this scheduler's observed
        TTFT and queue-wait distributions (sketch-backed; 0 before SLO
        sketches have samples). The fleet router's routing-signal
        precursor: rank replicas by the latency a tenant would
        experience, not just by queue depth."""
        base = len(self.queue) * self._chunk_ewma
        if self.slo is None:
            return base
        ttft_p50 = self.slo.quantile("ttft", 0.5)
        wait_p50 = self.slo.quantile("queue_wait", 0.5)
        if ttft_p50 is None or wait_p50 is None:
            return base
        return base + max(ttft_p50 - wait_p50, 0.0)

    # -- internals ---------------------------------------------------------

    def _build_tuner(self, cfg: TunerConfig, engine: Engine) -> Controller:
        """Validate the declared ladders against the engine's WARMED
        variant ladders and build the controller. Device-shaping knobs
        may only name compiled variants (the serving.tuner pre-warm
        contract — WARMUP-COVERAGE pins the engine half statically);
        host knobs are checked for shape only."""
        if cfg.decode_chunk is not None:
            bad = [c for c in cfg.decode_chunk
                   if c not in engine.decode_chunks]
            if bad:
                raise ValueError(
                    f"tuner decode_chunk candidates {bad} are not "
                    f"pre-warmed step variants "
                    f"{engine.decode_chunks} — declare them in "
                    f"EngineConfig.decode_chunks so warmup() compiles "
                    f"them (switching to an unwarmed variant would "
                    f"recompile mid-serve)")
        if cfg.spec_k is not None:
            bad = [k for k in cfg.spec_k
                   if k != 0 and k not in engine.spec_ks]
            if bad:
                raise ValueError(
                    f"tuner spec_k candidates {bad} are not pre-warmed "
                    f"spec variants {engine.spec_ks} — declare them in "
                    f"EngineConfig.spec_ks")
        base = {
            "decode_chunk": engine.engine_cfg.decode_chunk,
            "pipeline_depth": self.pipeline_depth,
            # 0 is the ladder spelling of "unlimited" (None)
            "max_admit_batch": self.max_admit_batch or 0,
            "spec_k": engine.engine_cfg.spec_k,
        }
        tele = self.telemetry
        ctl = Controller(
            cfg, base, recorder=self.recorder,
            on_switch=(None if tele is None
                       else lambda knob: tele.tuner_switches[knob].inc()))
        if tele is not None:
            tele.bind_tuner(ctl.knobs)
        return ctl

    def _tuner_freeze_cause(self) -> Optional[str]:
        """The hard-freeze condition, re-evaluated each tick: the
        controller must not steer (or measure) while constrained
        decoding serializes the loop, while any slot is re-deriving a
        pre-fault stream (:meth:`_exclusion_cause` — THE shared
        spelling), or while the health machine drains."""
        if self.health.state == HEALTH_DRAINING:
            return "drain"
        return self._exclusion_cause()

    def _sync_tuner(self) -> None:
        """Tick-start controller sync: freeze/thaw from the live
        exclusion conditions, then apply the current operating point's
        HOST knobs (pipeline depth, admission cap) so this tick's
        admissions and drain target already run the point the next
        dispatch will use."""
        tn = self._tuner
        if tn is None:
            return
        cause = self._tuner_freeze_cause()
        if cause is not None:
            tn.freeze(cause)
        else:
            tn.thaw()
        point = tn.current_point()
        if "pipeline_depth" in point:
            self.pipeline_depth = point["pipeline_depth"]
        if "max_admit_batch" in point:
            self.max_admit_batch = point["max_admit_batch"] or None
        if self.telemetry is not None:
            self.telemetry.tuner_state.set(tn.state())
            for k, v in tn.incumbent.items():
                self.telemetry.tuner_knob[k].set(v)

    def _on_slo_state(self, obj: SLOObjective, old: str,
                      new: str) -> None:
        """Burn-machine transition hook: count page-worthy alerts into
        the registry (the transition + alert EVENTS are the monitor's
        own recorder job)."""
        if self.telemetry is None:
            return
        ch = self.telemetry.slo_children.get(obj.key())
        if ch is not None and new in ch["alerts"]:
            ch["alerts"][new].inc()

    def _sync_slo(self, now: float) -> None:
        """Tick-cadence SLO work: run any due burn-machine evaluation,
        and refresh the quantile/burn/state/budget gauges whenever one
        ran (gauge refresh is eval-cadence, never per-token)."""
        mon = self.slo
        if mon is None:
            return
        if not mon.tick(now) or self.telemetry is None:
            return
        for metric in SLO_METRICS:
            sk = mon.sketch(metric)
            if sk is None or not sk.count:
                continue
            for q, g in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                self.telemetry.slo_quantile[(metric, g)].set(
                    sk.quantile(q))
        for key, m in mon.machines.items():
            ch = self.telemetry.slo_children[key]
            ch["fast"].set(m.fast_burn)
            ch["slow"].set(m.slow_burn)
            ch["state"].set(SLO_STATE_CODE[m.state])
            ch["budget"].set(m.budget_remaining())

    def _guard_alarm_count(self) -> float:
        """Current value of the engine sentinel's recompile-alarm
        counter (0.0 when no registry-wired sentinel exists) — polled
        each tick so guard alarms degrade health automatically."""
        sent = getattr(self.engine, "_sentinel", None)
        return sent.alarms_total() if sent is not None else 0.0

    def _poll_guard_alarms(self) -> None:
        v = self._guard_alarm_count()
        if v > self._alarms_seen:
            self._alarms_seen = v
            if self.recorder is not None:
                self.recorder.record("guard_alarm", v)
            self.health.record_fault("recompile_alarm")
            self._maybe_dump("guard_alarm")

    def _backoff_wait_s(self) -> Optional[float]:
        """Seconds until the earliest retry-backoff gate opens, when
        that is the ONLY remaining work (else None)."""
        if self.active or self._inflight or self._chunked is not None \
                or not self.queue:
            return None
        now = self.clock()
        waits = []
        for r in self.queue:
            st = self._replay.get(r.request_id)
            if st is None or st.not_before <= now:
                return None  # something is admissible right now
            waits.append(st.not_before - now)
        return min(waits) + 1e-4

    def _dispatchable(self) -> bool:
        """Whether dispatching another chunk can produce ANY real
        token: some active slot must have token budget left beyond the
        columns already in flight for it. Without this guard a deep
        pipeline burns a guaranteed-all-pad chunk at every wave of
        finishes (the host only learns a slot died when it fetches the
        chunk that killed it). Early-eos finishes stay speculative —
        the host cannot predict them, so a chunk may still carry some
        pad lanes, exactly like a mid-chunk finish under
        ``decode_chunk`` — but a chunk that CANNOT pay for itself is
        never dispatched."""
        if not self.active:
            return False
        if self._inflight and any(
                a.request.constraint is not None
                for a in self.active.values()):
            # a constrained slot's vocab mask only advances once the
            # previous chunk's tokens are fetched — dispatching on top
            # of an in-flight chunk would decode against a stale mask,
            # so constrained traffic serializes the pipeline (depth
            # effectively 1 while any constrained request is active)
            return False
        if not self._inflight:
            return True
        # price each in-flight chunk at its max emission — decode_chunk
        # for plain chunks, decode_chunk*(spec_k+1) for speculative
        # ones (conservative: a spec chunk may emit fewer, in which
        # case the next tick's fetch corrects the estimate)
        cols: Dict[int, int] = {}
        for handle, snapshot, _, _, _ in self._inflight:
            for slot, act in snapshot.items():
                if self.active.get(slot) is act:
                    cols[slot] = cols.get(slot, 0) + handle.ncols
        return any(
            len(act.tokens) + cols.get(slot, 0) < act.request.max_tokens
            for slot, act in self.active.items())

    def _exclusion_cause(self) -> Optional[str]:
        """THE per-slot exclusion conditions, as a cause: a
        constrained request is active (its vocab mask advances per
        token — the decode_chunk==1 serialization from the constrained
        path extends to forcing plain chunks), or a fault replay is in
        flight (replay exactness is simplest to audit on the plain
        path; streams are bit-identical either way, this keeps the
        replay invariant independent of gate/tuner state). One
        spelling shared by the payoff gate's plain-forcing
        (:meth:`_plain_only`) and the tuner's freeze causes so the two
        can never disagree on the exclusions."""
        for act in self.active.values():
            if act.request.constraint is not None:
                return "constrained"
            if len(act.tokens) < act.suppress:
                return "replay"     # re-deriving a pre-fault stream
        return None

    def _plain_only(self) -> bool:
        """Whether speculative dispatch is excluded right now (see
        :meth:`_exclusion_cause`)."""
        return self._exclusion_cause() is not None

    def _use_spec(self) -> bool:
        """Whether the next chunk dispatches the speculative variant
        under the payoff gate (the non-tuner spec path)."""
        g = self._gate
        if g is None or self._plain_only():
            return False
        return g.want_spec(spec_inflight=sum(
            1 for entry in self._inflight if entry[0].spec))

    def _dispatch_chunk(self) -> bool:
        """Dispatch the next decode chunk if it can pay for itself;
        True when one went out. With a tuner, the controller picks the
        operating point (pre-warmed variant + host knobs) — or holds
        the dispatch while a probe chunk is still in flight (probe
        serialization). A dispatch-seam fault triggers recovery (every
        live slot was in the failing chunk's blast radius)."""
        if not self._dispatchable():
            return False
        tn = self._tuner
        point: Optional[Dict[str, int]] = None
        step_kw: Dict[str, Any] = {}
        if tn is not None:
            cause = self._exclusion_cause()
            if cause is not None:
                # re-evaluated AT dispatch, not just at tick start: a
                # constrained (or replaying) request admitted THIS
                # tick — after _sync_tuner's freeze check — must not
                # decode at the incumbent/probe chunk width (a >1
                # chunk would scan tokens 2..n against a stale vocab
                # mask: schema-invalid output, not just a bad sample)
                tn.freeze(cause)
            point = tn.want_dispatch(len(self._inflight))
            if point is None:
                return False    # a probe chunk is in flight — hold
            if "pipeline_depth" in point:
                # the depth knob applies at dispatch too: a probe
                # window's candidate depth governs its own chunks
                self.pipeline_depth = point["pipeline_depth"]
            if "decode_chunk" in point:
                step_kw["chunk"] = point["decode_chunk"]
            k = point.get("spec_k", 0)
            if k > 0 and not self._plain_only():
                step_kw["spec"], step_kw["spec_k"] = True, k
            else:
                # gate-owned speculation composes, EXCEPT during a
                # probe window: probe chunks force the plain path, or
                # an open gate (spec chunks are never observed — their
                # token counts reflect acceptance, not this point's
                # knobs) would starve the window of its probe_chunks
                # samples while serialization held the pipeline at one
                # in-flight chunk
                step_kw["spec"] = ("spec_k" not in point
                                   and tn.probe is None
                                   and self._use_spec())
                if "spec_k" in point:
                    # record the EFFECTIVE point: a plain-forced chunk
                    # (exclusion raced the tick-start freeze) must not
                    # be attributed to the spec operating point
                    point["spec_k"] = 0
            if tn.frozen is not None or (
                    step_kw["spec"] and "spec_k" not in tn.knobs):
                # never-observe sentinel: a frozen dispatch carries
                # replay/constrained traffic even if fetched after the
                # thaw, and a GATE-driven speculative chunk's token
                # count reflects the gate's acceptance, not this
                # point's knobs — folding either into the EWMAs would
                # poison exactly the comparison the controller makes
                point = None
        else:
            step_kw["spec"] = self._use_spec()
        t0 = self.clock()
        try:
            handle = self.engine.step_async(**step_kw)
        except Exception as e:  # device error escaping the dispatch
            self._recover(self.clock(), cause="dispatch", detail=str(e),
                          affected=[a.request for _, a in
                                    sorted(self.active.items())])
            return False
        t1 = self.clock()
        if self.spans is not None:
            # the host-side cost of getting the chunk onto the device —
            # the half of the old engine.step section the pipeline
            # cannot hide
            self.spans.section_at("engine.dispatch", t0, t1)
        # snapshot the live slots: by the time this chunk is fetched,
        # some may have been released (finish seen in an earlier chunk,
        # deadline retire) and their columns must be dropped
        self._inflight.append((handle, dict(self.active), t0,
                               len(self._inflight) + 1, point))
        if self.recorder is not None:
            self.recorder.record("dispatch", handle.spec, handle.ncols,
                                 len(self._inflight), len(self.active))
        if self.telemetry is not None:
            self.telemetry.inflight.set(len(self._inflight))
        return True

    def _collect_oldest(self) -> None:
        handle, snapshot, t_dispatch, depth_at_dispatch, point = \
            self._inflight.popleft()
        t0 = self.clock()
        try:
            tokens, logprobs, finished = handle.fetch()
        except Exception as e:  # device error escaping the fetch
            self._recover(self.clock(), cause="fetch", detail=str(e),
                          affected=[a.request
                                    for s, a in sorted(snapshot.items())
                                    if self.active.get(s) is a])
            return
        now = self.clock()
        tele = self.telemetry
        if tele is not None:
            tele.inflight.set(len(self._inflight))
        if self.spans is not None:
            # the blocking wait for the chunk's value — under pipelining
            # this shrinks toward zero while engine.dispatch stays put
            self.spans.section_at("engine.fetch", t0, now)
            for slot, act in snapshot.items():
                if self.active.get(slot) is act:
                    self.spans.mark(act.request.request_id,
                                    spans_mod.PHASE_DECODE)
        # chunk-latency EWMA + watchdog: a dispatch that took longer
        # than the timeout to yield its value is flagged as hung (the
        # tokens may still be good — the chunk proceeds). A tripped
        # chunk is EXCLUDED from the EWMA: it is already accounted as
        # a fault, and folding a 30 s hang into the overload estimator
        # would shed every deadlined request in the queue against a
        # latency the healthy engine does not have. The EWMA sample is
        # normalized by the pipeline depth at dispatch: at depth d the
        # dispatch-to-fetch wall includes waiting behind d-1 earlier
        # in-flight chunks, and pricing the queue with the un-divided
        # wall would overstate slot turnover ~d× and shed requests
        # that would have met their deadlines
        # still-live snapshot rows — THE liveness condition shared by
        # the fetch event, the gate's tokens-per-wave denominator, and
        # the latency denominator below (computed once so they can
        # never disagree)
        live_rows = [s for s, a in snapshot.items()
                     if self.active.get(s) is a]
        chunk_wall = max(now - t_dispatch, 0.0)
        rec = self.recorder
        if rec is not None:
            rec.record("fetch", handle.spec, handle.ncols, chunk_wall,
                       len(live_rows))
        if chunk_wall > self.resilience.watchdog_timeout_s:
            self._watchdog_trips += 1
            if rec is not None:
                rec.record("watchdog", chunk_wall)
            if self._tuner is not None:
                # a tripped chunk is never observed (below) — without
                # this freeze, a probe window whose candidate keeps
                # hanging would never accumulate its probe_chunks
                # samples and the controller would re-dispatch the
                # pathological variant forever. The freeze aborts the
                # window (recorded, so decision replay sees it) and
                # the next clean tick thaws and moves on.
                self._tuner.freeze("watchdog")
            self.health.record_fault("watchdog")
            self._maybe_dump("watchdog")
            if tele is not None:
                tele.watchdog.inc()
        else:
            sample = chunk_wall / max(depth_at_dispatch, 1)
            self._chunk_ewma = sample if self._chunk_ewma == 0.0 \
                else 0.7 * self._chunk_ewma + 0.3 * sample
        # NaN/garbage quarantine: an out-of-vocab token id ANYWHERE in
        # the batch means the step (and the cache it wrote) cannot be
        # trusted — drop the whole chunk before unpacking a single
        # token and rebuild, even when every corrupt lane belongs to a
        # slot already released (the cache those lanes share is still
        # poisoned). Only still-live corrupt lanes are charged a
        # retry; everyone else replays for free. One whole-array
        # min/max pass exits the healthy case before any per-slot
        # work (this runs on every chunk)
        vocab = self.engine.cfg.vocab_size
        if tokens.size and (int(tokens.min()) < 0
                            or int(tokens.max()) >= vocab):
            bad = [act.request for slot, act in sorted(snapshot.items())
                   if self.active.get(slot) is act
                   and bool(((tokens[slot] < 0)
                             | (tokens[slot] >= vocab)).any())]
            self._recover(
                now, cause="invalid_token",
                detail="invalid token id in decode batch "
                "(NaN-poisoned step)", affected=bad)
            return
        n_cols = tokens.shape[1]
        valid = handle.valid    # spec chunks: which columns are real
        # speculative accounting + payoff gate: tokens-per-wave over
        # the still-live snapshot rows (a live wave always emits its
        # column-0 token, so live waves = True column-0 flags), and the
        # chunk-wall EWMAs per variant the break-even compares. A
        # watchdog-tripped chunk is excluded exactly like the overload
        # EWMA above.
        g = self._gate
        if chunk_wall <= self.resilience.watchdog_timeout_s \
                and (g is not None or handle.spec):
            sample = chunk_wall / max(depth_at_dispatch, 1)
            if handle.spec:
                # per-wave accounting runs for EVERY spec chunk —
                # gate-driven or tuner-driven (the tuner's spec_k knob
                # has no gate, but acceptance telemetry must not go
                # dark when the controller owns the choice)
                self._spec_chunks += 1
                tpw = None
                rows = live_rows
                if rows and valid is not None:
                    v = valid[rows]
                    live_waves = int(v[:, ::handle.spec_k + 1].sum())
                    emitted = int(v.sum())
                    if live_waves:
                        tpw = emitted / live_waves
                        drafted = handle.spec_k * live_waves
                        self._spec_drafted += drafted
                        self._spec_accepted += emitted - live_waves
                        if tele is not None:
                            tele.spec_drafted.inc(drafted)
                            tele.spec_accepted.inc(emitted - live_waves)
                if g is not None:
                    g.observe_spec(sample, tpw)
                if self.spans is not None:
                    # the verify forward's host window: dispatch to
                    # value of the speculative chunk
                    self.spans.section_at("engine.verify", t_dispatch,
                                          now)
            elif g is not None:
                g.observe_plain(sample)
            if g is not None:
                st = g.state()
                if st != self._gate_state_seen:
                    # a payoff-gate transition is a scheduling decision
                    # — log it once per flip, not per chunk
                    self._gate_state_seen = st
                    if rec is not None:
                        rec.record("spec_gate", st, g.accept_ewma,
                                   g.break_even())
                if tele is not None:
                    tele.spec_gate.set(st)
                    tele.spec_accept_ewma.set(g.accept_ewma)
        # in-flight latency of this chunk (dispatch -> value); the
        # decode-time split dedups the overlap so pipelined chunks
        # don't double-count wall time. Spec chunks price latency per
        # REAL emitted token (pad lanes are not tokens).
        if valid is None:
            per_tok = max(now - t_dispatch, 0.0) / n_cols
        else:
            mean_emitted = (valid[live_rows].sum() / len(live_rows)
                            if live_rows else 0.0)
            per_tok = (max(now - t_dispatch, 0.0)
                       / max(mean_emitted, 1.0))
        self._decode_time += now - max(self._decode_mark, t_dispatch)
        self._decode_mark = now
        chunk_tokens = 0    # actual ingested emissions (the tuner's
        # tokens-per-second numerator: pad columns past a finish are
        # honestly NOT tokens, so an over-wide chunk scores its waste)
        for j in range(n_cols):
            for slot, act in snapshot.items():
                # a slot released since dispatch (earlier chunk/column
                # finish, a host-side stop, or a deadline retire
                # landing mid-flight) is skipped: the device emits pad
                # for done lanes, and a retired request's in-flight
                # tokens belong to a completion that already closed.
                # Spec chunks additionally skip non-valid columns —
                # rejected draft lanes emit pad without being tokens
                # (the StopMatcher and constraint DFA see the accepted
                # prefix only).
                if self.active.get(slot) is not act:
                    continue
                if valid is not None and not valid[slot, j]:
                    continue
                tok = int(tokens[slot, j])
                done = bool(finished[slot, j])
                reason = None
                if done:
                    eos = act.request.eos_token_id
                    reason = (FINISH_EOS
                              if eos is not None and tok == eos
                              else FINISH_LENGTH)
                chunk_tokens += 1
                if self._ingest(slot, act, tok,
                                float(logprobs[slot, j]), now,
                                device_done=done, device_reason=reason,
                                latency=per_tok) == _RECOVERED:
                    return  # recovery rebuilt everything mid-unpack
        tn = self._tuner
        if tn is not None and point is not None and chunk_wall <= \
                self.resilience.watchdog_timeout_s:
            # the control plane's one input: realized tokens at this
            # chunk's operating point (watchdog-tripped chunks are
            # excluded exactly like the overload EWMA; a frozen
            # controller ignores the call). Recorded as tuner_obs, so
            # telemetry.replay re-derives every decision from it.
            tn.observe(point, chunk_tokens, chunk_wall,
                       depth_at_dispatch)
        # a chunk landed end-to-end: recovery streak for the health
        # machine, and the rebuild-storm counter resets
        self._consecutive_rebuilds = 0
        self.health.record_progress()
        # the fetch boundary is the journal's durability point: every
        # token this chunk streamed is on disk (per fsync policy)
        # before the next dispatch can build on it
        self._journal_commit()

    # -- token emission (stop sequences, constraints, logprobs) -------------

    def _emit(self, act: _Active, tok: int, lp: float, *,
              finished: bool, reason: Optional[str],
              latency: Optional[float] = None) -> None:
        """Append one client-visible token to ``act``'s stream and its
        :class:`StreamEvent` — suppressed (counted, no event) while the
        token re-derives a pre-fault stream prefix during replay."""
        act.tokens.append(tok)
        act.logprobs.append(lp)
        tele = self.telemetry
        if len(act.tokens) <= act.suppress:
            # re-derived token, already streamed before the fault —
            # suppress the duplicate event
            if tele is not None:
                tele.replayed.inc()
            return
        self._tokens_emitted += 1
        # the WFQ deficit counter charges on ACTUAL served tokens —
        # fairness settles on delivered service, not admission-time
        # estimates (replay-suppressed re-derivations were charged
        # when first streamed, so they are not double-billed)
        self.tenants.on_tokens(act.request.tenant, 1)
        if latency is not None:
            self._decode_tokens += 1
            self.token_latency_stats.add(latency)
            if self.slo is not None:
                self.slo.observe("token_latency", latency,
                                 act.request.tenant)
            if tele is not None:
                tele.token_latency.observe(latency)
        if tele is not None:
            tele.tokens.inc()
            tele.tenant(act.request.tenant)["tokens"].inc()
        self.events.append(StreamEvent(
            act.request.request_id, tok, finished, reason, logprob=lp))

    def _flush_held(self, act: _Active,
                    latency: Optional[float] = None) -> None:
        """Stream every token the stop matcher held back — a non-stop
        finish (eos/length/deadline/error) emits the tail instead of
        trimming it."""
        if act.matcher is None:
            return
        for t, l in act.matcher.flush():
            self._emit(act, t, l, finished=False, reason=None,
                       latency=latency)

    def _ingest(self, slot: int, act: _Active, tok: int, lp: float,
                now: float, *, device_done: bool,
                device_reason: Optional[str],
                latency: Optional[float] = None) -> int:
        """Fold ONE generated token into a live request: stop-sequence
        matching (with trimmed emission), schema-constraint advance +
        next-mask upload, event emission, and release when the token
        finishes the request (device eos/budget, stop match, or
        constraint completion). Returns an ``_LIVE`` / ``_RELEASED`` /
        ``_RECOVERED`` outcome; ``_RECOVERED`` means a retire-seam
        fault rebuilt the engine mid-call and the caller's loop state
        is stale."""
        matched = False
        if act.matcher is not None:
            flushed, matched = act.matcher.push(tok, lp)
        else:
            flushed = [(tok, lp)]
        cons = act.request.constraint
        cons_done = False
        if cons is not None and not matched:
            cons.advance(tok)
            cons_done = bool(cons.done)
            if not cons_done and not device_done:
                # the DFA advanced: the NEXT dispatch must decode this
                # slot against the new allowed set
                self.engine.set_slot_mask(slot, cons.allowed_tokens())
        if (device_done or cons_done) and act.matcher is not None \
                and not matched:
            # non-trim finish: the held tail streams out
            flushed = flushed + act.matcher.flush()
        host_stop = matched or cons_done
        finishing = device_done or host_stop
        reason = ((FINISH_STOP if host_stop else device_reason)
                  if finishing else None)
        last = len(flushed) - 1
        for i, (t, l) in enumerate(flushed):
            fin = finishing and not matched and i == last
            self._emit(act, t, l, finished=fin,
                       reason=reason if fin else None, latency=latency)
        if matched:
            # trimmed stop: no token carries the finish — close the
            # stream with a token-less finished event (the deadline/
            # abort pattern)
            self.events.append(StreamEvent(
                act.request.request_id, None, True, reason))
        if not finishing:
            return _LIVE
        if host_stop and not device_done:
            # host-side finish: the device lane is still live — retire
            # it so later chunks stop burning its budget (in-flight
            # chunks' columns for this slot are dropped by the
            # snapshot identity check, exactly like a deadline retire)
            try:
                self.engine.retire(slot)
            except Exception as e:  # device error escaping retire
                self._release(slot, reason)
                self._recover(now, cause="retire", detail=str(e),
                              affected=[])
                return _RECOVERED
        self._release(slot, reason)
        return _RELEASED

    def _reset_free(self) -> List[int]:
        """Every slot free, pop order = slot order."""
        self._free = list(range(self.engine.slots))[::-1]
        return self._free

    def _abort(self, request: Request, reason: str, now: float, *,
               act: Optional[_Active] = None,
               error: Optional[str] = None) -> None:
        """Terminal non-success outcome (timeout shed/expiry, fault
        error): one finished StreamEvent + a completion carrying the
        longest stream the client saw — the live slot's tokens, or the
        replay snapshot when a fault interrupted mid-replay and the
        re-derivation had not caught up."""
        if act is not None:
            self._flush_held(act)
        st = self._replay.pop(request.request_id, None)
        tokens = list(act.tokens) if act is not None else []
        lps = list(act.logprobs) if act is not None else []
        if st is not None and len(st.tokens) > len(tokens):
            tokens, lps = st.tokens, st.logprobs
        ttft = None
        if act is not None and act.first_token_time is not None:
            ttft = act.first_token_time - request.arrival_time
        self.events.append(StreamEvent(
            request.request_id, None, True, reason, error=error))
        self._complete(request, tokens, reason, ttft=ttft, now=now,
                       logprobs=lps)

    # -- failure isolation + recovery --------------------------------------

    def _recover(self, now: float, *, cause: str, detail: str,
                 affected: Sequence[Request],
                 batch_reqs: Sequence[Request] = ()) -> None:
        """Quarantine + rebuild + deterministic replay. ``affected``
        requests were in the fault's blast radius: they are charged a
        retry (bounded, exponential backoff) and get an ``error``
        stream event; exhaustion completes them with the ``error``
        reason. Every other interrupted request — live slots, plus
        ``batch_reqs`` from a failed admission call that never reached
        a slot — replays for free. Replay = re-admit from the prompt:
        generation is per-request deterministic, so the regenerated
        stream is bit-identical and the already-streamed prefix
        (tracked per request in ``_replay``) is re-derived silently."""
        tele = self.telemetry
        rec = self.recorder
        rcfg = self.resilience
        if self._tuner is not None:
            # the rebuild bracket is a hard freeze: in-flight chunks
            # are discarded unmeasured, and the replay traffic that
            # follows re-freezes at the next tick's cause evaluation
            self._tuner.freeze("rebuild")
        if rec is not None:
            rec.record("fault", cause, detail, len(affected))
        self.health.record_fault(cause)
        if tele is not None and cause in tele.faults:
            tele.faults[cause].inc()
        # in-flight chunks were dispatched against the poisoned
        # buffers: discard them UNFETCHED (their futures may hold the
        # error; the replay re-derives anything they carried)
        self._inflight.clear()
        if tele is not None:
            tele.inflight.set(0)
        self._consecutive_rebuilds += 1
        if self._consecutive_rebuilds > rcfg.max_consecutive_rebuilds:
            self.queue.extendleft(reversed(list(batch_reqs)))
            self._fail_all(f"recovery storm ({cause}: {detail})", now)
            return
        # interrupted work, slot order first (they were admitted
        # earliest), then the failed admission batch (they were at the
        # queue's front moments ago)
        interrupted: List[Tuple[Request, Optional[_Active]]] = [
            (act.request, act)
            for _, act in sorted(self.active.items())]
        interrupted += [(r, None) for r in batch_reqs]
        if self._chunked is not None:
            # a mid-chunked fault: the half-ingested prompt replays
            # from scratch like any other interrupted request
            ca, cr = self._chunked
            self._chunked = None
            if all(r.request_id != cr.request_id
                   for r, _ in interrupted):
                interrupted.append((cr, None))
        self.active.clear()
        self._reset_free()
        # always rebuild: even when the fault was detected host-side
        # (invalid tokens) or the exception left the engine formally
        # unpoisoned, the donated buffers were rebound across the
        # failing call and cannot be trusted
        self.engine.rebuild_slots()
        self._rebuilds += 1
        if rec is not None:
            rec.record("rebuild", cause,
                       max(self.clock() - now, 0.0),
                       self._consecutive_rebuilds)
        if tele is not None:
            tele.rebuilds.inc()
            tele.active_slots.set(0)
        if self.spans is not None:
            self.spans.section_at("engine.rebuild", now, self.clock())
        affected_ids = {r.request_id for r in affected}
        front: List[Request] = []
        for r, act in interrupted:
            st = self._replay.setdefault(r.request_id, _ReplayState())
            if act is not None and len(act.tokens) > len(st.tokens):
                # the last known-good snapshot: everything this request
                # streamed before the fault, re-derived on replay. Only
                # ever GROW it — a second fault landing mid-replay sees
                # act.tokens shorter than what was already streamed
                # (the replay had not caught up yet), and shrinking the
                # snapshot would re-emit the tail as duplicates.
                # Matcher-held tokens are NOT in the snapshot: they
                # were never streamed, and the replayed matcher
                # re-derives (and re-holds) them deterministically
                st.tokens = list(act.tokens)
                st.logprobs = list(act.logprobs)
            if rec is not None:
                rec.record("replay", r.request_id, len(st.tokens))
            if r.request_id in affected_ids:
                st.attempts += 1
                if st.attempts > rcfg.max_retries:
                    if rec is not None:
                        rec.record("retry_exhausted", r.request_id,
                                   st.attempts)
                    self.health.record_fault("retry_exhausted")
                    self._retry_exhausted += 1
                    if self.on_evict is not None:
                        # fleet hand-off: this replica gave up on the
                        # request, but another may serve it — the
                        # router resubmits with the emitted prefix so
                        # the client stream continues, not errors
                        self._evicted_requests += 1
                        self._replay.pop(r.request_id, None)
                        self._req_records.pop(r.request_id, None)
                        self.on_evict(
                            [EvictedRequest(r, list(st.tokens),
                                            list(st.logprobs))],
                            f"retry_exhausted ({cause}: {detail})")
                        continue
                    self._abort(r, FINISH_ERROR, now, act=act,
                                error=f"{cause}: {detail}; "
                                f"{rcfg.max_retries} retries exhausted")
                    continue
                st.not_before = now + rcfg.backoff_s(st.attempts)
                self._retries += 1
                if rec is not None:
                    rec.record("retry", r.request_id, st.attempts)
                if tele is not None:
                    tele.retries.inc()
                self.events.append(StreamEvent(
                    r.request_id, None, False, None,
                    error=f"{cause}: {detail}; retry "
                    f"{st.attempts}/{rcfg.max_retries}"))
                if self.spans is not None:
                    self.spans.mark(r.request_id, spans_mod.PHASE_ERROR,
                                    note=cause)
            front.append(r)
        self.queue.extendleft(reversed(front))
        if tele is not None:
            tele.queue_depth.set(len(self.queue))
        # the post-mortem bundle lands AFTER the recovery bracket, so
        # it carries the fault AND its rebuild/replay/retry events
        self._maybe_dump(f"fault-{cause}")

    def _fail_all(self, cause: str, now: float) -> None:
        """Terminal: abort every queued/active request with an
        ``error`` outcome (partial streams preserved) and mark the
        health machine failed. The process survives — callers see
        completions, not a crash. The terminal bundle dumps FIRST,
        while the queue/slot state it should explain still exists.
        With an :attr:`on_evict` hook, interrupted work is handed over
        as :class:`EvictedRequest` records instead of error outcomes —
        the fleet failover path."""
        if self.recorder is not None:
            self.recorder.record("failed", cause)
        self._maybe_dump("failed")
        self.health.fail(cause)
        if self.on_evict is not None:
            self._evict_all(cause)
            return
        for slot, act in sorted(self.active.items()):
            self._abort(act.request, FINISH_ERROR, now, act=act,
                        error=cause)
            self.engine.free_slot(slot)
        if self._chunked is not None:
            ca, cr = self._chunked
            self._chunked = None
            self.engine.free_slot(ca.slot)
            self._abort(cr, FINISH_ERROR, now, error=cause)
        self.active.clear()
        self._reset_free()
        for r in self.queue:
            self._abort(r, FINISH_ERROR, now, error=cause)
        self.queue.clear()
        for rid, pk in sorted(self._parked.items()):
            self.engine.drop_parked(rid)
            self._abort(pk.act.request, FINISH_ERROR, now, act=pk.act,
                        error=cause)
        self._parked.clear()
        self._resume_q.clear()
        self._replay.clear()
        self._inflight.clear()
        if self.telemetry is not None:
            self.telemetry.queue_depth.set(0)
            self.telemetry.active_slots.set(0)
            self.telemetry.inflight.set(0)

    def eject_all(self, cause: str) -> None:
        """Router-facing: hand EVERY queued/active request to the
        :attr:`on_evict` hook with its emitted prefix and clear this
        scheduler's work — the circuit-breaker eviction (the engine
        stays alive; the caller typically ``rebuild_slots()`` right
        after, since in-flight chunks are discarded unfetched)."""
        if self.on_evict is None:
            raise ValueError(
                "eject_all needs an on_evict hook — without one the "
                "evicted requests would simply vanish")
        self._evict_all(cause)

    def _evict_all(self, cause: str) -> None:
        """Hand every interrupted request (active slots first — they
        were admitted earliest — then any chunked admission, then the
        queue) to :attr:`on_evict` with its longest client-visible
        stream, clearing this scheduler's work WITHOUT emitting error
        events or completions: the fleet router owns their fate now.
        In-flight chunks are discarded unfetched — anything they
        carried re-derives on the healthy replica."""
        evicted: List[EvictedRequest] = []

        def take(request: Request, act: Optional[_Active]) -> None:
            st = self._replay.pop(request.request_id, None)
            tokens = list(act.tokens) if act is not None else []
            lps = list(act.logprobs) if act is not None else []
            if st is not None and len(st.tokens) > len(tokens):
                # mid-replay: the pre-fault stream is the longest the
                # client saw — never hand over a shrunk snapshot
                tokens, lps = list(st.tokens), list(st.logprobs)
            # the router owns these streams now: journaled finished
            # ("evicted") so a crash-restart from THIS replica's
            # journal never resubmits work the fleet already failed
            # over — that would fork the client stream
            self._journal_finish(request, tokens, lps, "evicted")
            self._req_records.pop(request.request_id, None)
            evicted.append(EvictedRequest(request, tokens, lps))

        for slot, act in sorted(self.active.items()):
            take(act.request, act)
            self.engine.free_slot(slot)
        if self._chunked is not None:
            ca, cr = self._chunked
            self._chunked = None
            self.engine.free_slot(ca.slot)
            take(cr, None)
        for r in self.queue:
            take(r, None)
        for rid, pk in sorted(self._parked.items()):
            self.engine.drop_parked(rid)
            take(pk.act.request, pk.act)
        self._parked.clear()
        self._resume_q.clear()
        self.active.clear()
        self.queue.clear()
        self._reset_free()
        self._replay.clear()
        self._inflight.clear()
        self._evicted_requests += len(evicted)
        if self.telemetry is not None:
            self.telemetry.queue_depth.set(0)
            self.telemetry.active_slots.set(0)
            self.telemetry.inflight.set(0)
        # the evict-finishes must be durable BEFORE the router
        # resubmits the work elsewhere — a crash in between would
        # otherwise recover requests another replica is now serving
        self._journal_commit()
        self.on_evict(evicted, cause)

    # -- durable request journal (serving.journal) ---------------------------

    def _jlog(self, kind: str, **fields) -> None:
        """Append one journal record (no-op without a journal) and
        surface it in the flight recorder — journal growth is itself
        a host decision a post-mortem wants on the timeline."""
        j = self.journal
        if j is None:
            return
        rot = j.rotations
        seq = j.append(kind, **fields)
        rec = self.recorder
        if rec is not None:
            rec.record("journal_append", seq, kind,
                       j.last_append_bytes)
            if j.rotations != rot and j.last_sealed is not None:
                rec.record("journal_rotate", *j.last_sealed)

    def _journal_submit(self, request: Request, now: float) -> None:
        """Journal an accepted request — the replayable
        ``_record_request`` row, with the absolute deadline converted
        to REMAINING budget (a monotonic clock does not survive a
        restart; recovery re-bases it)."""
        if self.journal is None:
            return
        row = dict(self._req_records[request.request_id])
        row.pop("arrival", None)
        deadline = row.pop("deadline", None)
        row["deadline_remaining"] = (
            None if deadline is None else max(deadline - now, 0.0))
        if row.get("adapter"):
            # the numeric id is generation-local (a recovered engine
            # re-assigns ids sequentially and may reuse a skipped
            # registration's); the NAME is the stable cross-recovery
            # key replay maps the request back through
            meta = self.engine._adapter_meta.get(
                int(row["adapter"]), {})
            row["adapter_name"] = meta.get("name")
        self._jlog("submit", **row)
        self._journal_len[request.request_id] = 0

    def _journal_extend(self, rid: str, tokens, logprobs) -> None:
        """Journal the growth of one stream's emitted prefix since the
        last extend. Absolute start offsets make replay idempotent —
        the property compaction's crash-safety rests on. Unknown ids
        (terminal-at-submit, pre-journal requests) are skipped."""
        jl = self._journal_len.get(rid)
        if jl is None or len(tokens) <= jl:
            return
        self._jlog("extend", request_id=rid, start=jl,
                   tokens=[int(t) for t in tokens[jl:]],
                   logprobs=[float(x) for x in logprobs[jl:]])
        self._journal_len[rid] = len(tokens)

    def _journal_commit(self) -> None:
        """The fetch-boundary durability point: extend every live
        stream (active slots AND replay snapshots — a preempted or
        parked conversation's prefix lives in ``_replay``), then
        fsync per the journal's policy, then let auto-compaction run.
        Registry counters refresh here by delta, off the per-token
        path."""
        j = self.journal
        if j is None:
            return
        for act in self.active.values():
            self._journal_extend(act.request.request_id, act.tokens,
                                 act.logprobs)
        for rid, st in self._replay.items():
            self._journal_extend(rid, st.tokens, st.logprobs)
        j.commit()
        j.maybe_compact()
        tele = self.telemetry
        if tele is not None:
            seen = self._j_seen
            for attr, handle in (
                    ("appends", tele.journal_appends),
                    ("rotations", tele.journal_rotations),
                    ("compactions", tele.journal_compactions)):
                d = getattr(j, attr) - seen[attr]
                if d:
                    handle.inc(d)
                    seen[attr] = getattr(j, attr)
            ds = j.fsync_s - seen["fsync_s"]
            if ds > 0:
                tele.journal_fsync.inc(ds)
                seen["fsync_s"] = j.fsync_s
            tele.journal_bytes.set(j.bytes_on_disk())
            tele.journal_lag.set(j.lag_bytes)

    def _journal_finish(self, request: Request, tokens, logprobs,
                        reason: str) -> None:
        """Journal a terminal outcome: the final extend (everything
        the client was streamed) then the finish record, so recovery
        never resubmits completed — or fleet-evicted — work."""
        if self.journal is None:
            return
        rid = request.request_id
        if rid not in self._journal_len:
            return
        self._journal_extend(rid, tokens, logprobs or [])
        self._journal_len.pop(rid, None)
        self._jlog("finish", request_id=rid, reason=reason)

    # -- flight recorder + post-mortem bundles -------------------------------

    def _record_request(self, request: Request, now: float) -> None:
        """Start the replayable record of one accepted request — the
        bundle's ``requests.jsonl`` row (prompt/sampling/seed; the
        emitted prefix attaches at completion or dump time). Kept even
        without a recorder: dumps are most wanted for runs nobody
        thought to instrument."""
        sp = request.sampling
        self._req_records[request.request_id] = {
            "order": self._submit_seq,
            "request_id": request.request_id,
            "prompt": [int(t) for t in request.prompt],
            "max_tokens": request.max_tokens,
            "temperature": sp.temperature,
            "top_k": sp.top_k,
            "top_p": sp.top_p,
            "seed": sp.seed,
            "eos_token_id": request.eos_token_id,
            "stop": ([[int(t) for t in s] for s in request.stop]
                     if request.stop else None),
            "constrained": request.constraint is not None,
            "deadline": request.deadline,
            "arrival": now,
            # the tenancy pair: replay resubmits with the same tenant
            # (fair-queue decisions re-derive) and the same adapter
            # row (seeded registrations rebuild the exact weights, so
            # the replayed stream is bit-identical)
            "tenant": request.tenant,
            "adapter": request.adapter,
        }
        self._submit_seq += 1

    def _on_health_transition(self, old: str, new: str,
                              cause: Optional[str]) -> None:
        if self.recorder is not None:
            self.recorder.record("health", old, new, cause)

    def _maybe_dump(self, cause: str) -> None:
        """Auto-dump gate: a bundle per trigger WAVE (faults, their
        health transitions, and their retries land in one tick — one
        bundle explains them all), bounded by ``max_auto_bundles`` so a
        fault storm cannot fill the disk with near-identical evidence.
        Disk errors are swallowed — losing a bundle must never take
        down the serving loop that survived the fault itself."""
        if self.bundle_dir is None \
                or self._auto_bundles >= self.max_auto_bundles \
                or self._last_dump_token == self._dump_token:
            return
        self._last_dump_token = self._dump_token
        self._auto_bundles += 1
        try:
            self.dump_bundle(cause)
        except OSError:
            pass

    def dump_bundle(self, cause: str = "manual",
                    bundle_dir: Optional[str] = None) -> str:
        """Write a self-contained post-mortem bundle directory and
        return its path: manifest (cause, health, ``summary()``,
        versions, caller ``bundle_meta``), flight-recorder event log
        (``events.jsonl``), engine/scheduler config (``config.json``
        — everything ``apex_tpu.telemetry.replay`` needs to rebuild
        the run), per-request replay records (``requests.jsonl``),
        plus registry snapshot / Chrome-trace spans / fault-plan
        record when those exist. Atomic (same-dir tmp +
        ``os.replace``): a reader sees a complete bundle or none.

        Safe to call from another thread (the ``/debug/bundle``
        trigger, a SIGUSR handler): the payload walk takes C-level
        (GIL-atomic) snapshots of the mutable maps, and the build is
        retried if the serving loop still manages to mutate a
        structure mid-iteration — the bundle is a best-effort snapshot
        of a moving system, but it is always internally well-formed."""
        base = bundle_dir or self.bundle_dir
        if base is None:
            raise ValueError(
                "no bundle directory: pass bundle_dir here or "
                "Scheduler(bundle_dir=...)")
        for attempt in range(3):
            try:
                files = self._bundle_payload(cause)
                break
            except RuntimeError:  # dict/set mutated during iteration
                if attempt == 2:
                    raise
        slug = "".join(c if c.isalnum() else "-" for c in cause)[:40]
        while True:
            name = f"bundle-{self._bundle_counter:04d}-{slug}"
            path = os.path.join(base, name)
            self._bundle_counter += 1
            if not os.path.exists(path):
                break
        path = flightrec_mod.write_bundle(path, files)
        self.bundles_written.append(path)
        if self.recorder is not None:
            self.recorder.record("bundle", cause,
                                 os.path.basename(path))
        return path

    def _bundle_payload(self, cause: str) -> Dict[str, object]:
        engine = self.engine
        rec = self.recorder
        # completed records first, then live (queued/active) ones with
        # the client-visible stream they have so far — the longest of
        # the live slot's tokens and the replay snapshot (mid-replay
        # the snapshot is what the client actually saw)
        # list()/dict() of a dict are single C calls — GIL-atomic
        # snapshots, so a cross-thread dump never iterates a map the
        # serving loop is mutating (the comprehensions below run over
        # the snapshots, not the live structures)
        requests = [dict(r) for r in self._req_done.values()]
        by_id = {a.request.request_id: a
                 for a in list(self.active.values())}
        parked = {pk.act.request.request_id: pk.act
                  for pk in list(self._parked.values())}
        for rid, row in list(self._req_records.items()):
            row = dict(row)
            act = by_id.get(rid) or parked.get(rid)
            toks = list(act.tokens) if act is not None else []
            st = self._replay.get(rid)
            if st is not None and len(st.tokens) > len(toks):
                toks = list(st.tokens)
            row["emitted"] = toks
            row["status"] = ("active" if rid in by_id
                             else "parked" if rid in parked
                             else "queued")
            requests.append(row)
        requests.sort(key=lambda r: r["order"])
        manifest: Dict[str, object] = {
            "bundle_version": 1,
            "cause": cause,
            "wall_time": time.time(),
            "clock": self.clock(),
            "health": {"state": self.health.state,
                       "last_cause": self.health.last_cause},
            "summary": self.summary(),
            "flightrec": rec.summary() if rec is not None else None,
            "compiled": engine.compiled_cache_sizes(),
            "versions": flightrec_mod.versions(),
            "meta": self.bundle_meta,
        }
        sentinel = getattr(engine, "_sentinel", None)
        if sentinel is not None:
            manifest["recompile"] = sentinel.compiles_total()
        config: Dict[str, object] = {
            "engine": engine.describe(),
            "scheduler": {
                "max_queue": self.max_queue,
                "pipeline_depth": self._cfg_pipeline_depth,
                "max_admit_batch": self._cfg_max_admit_batch,
                "resilience": dataclasses.asdict(self.resilience),
                "spec_gate": (dataclasses.asdict(self._gate.cfg)
                              if self._gate is not None else None),
                # the tuner's ladders + policy AND its base operating
                # point: everything replay_decisions needs to re-run
                # the trajectory from the recorded observations
                "tuner": (dataclasses.asdict(self._tuner.cfg)
                          if self._tuner is not None else None),
                "tuner_base": (dict(self._tuner.base)
                               if self._tuner is not None else None),
                # weights/rates serialize as plain dicts so replay
                # rebuilds the same WFQ + rate policy
                "tenancy": (None if self._tenancy_cfg is None else {
                    "weights": dict(self._tenancy_cfg.weights),
                    "default_weight":
                        self._tenancy_cfg.default_weight,
                    "rates": dict(self._tenancy_cfg.rates),
                    "default_rate": self._tenancy_cfg.default_rate,
                    "burst_s": self._tenancy_cfg.burst_s,
                    "aging_per_s": self._tenancy_cfg.aging_per_s,
                }),
                # objectives + burn policy: everything replay_slo needs
                # to re-run the alert sequence from the recorded
                # evaluation inputs
                "slo": (self._slo_cfg.to_dict()
                        if self._slo_cfg is not None else None),
            },
        }
        files: Dict[str, object] = {
            "manifest.json": manifest,
            "config.json": config,
            "events.jsonl": (rec.to_dicts(rec.events())
                             if rec is not None else []),
            "requests.jsonl": requests,
        }
        if self._registry is not None:
            files["registry.json"] = self._registry.to_dict()
        if self.spans is not None:
            files["spans_trace.json"] = self.spans.to_chrome_trace()
            # raw span rows keep ABSOLUTE scheduler-clock times (the
            # Chrome trace rebases to its own t0), so the replay
            # report can merge spans and flight events on one axis
            raw = []
            for e in self.spans.events():
                if e[0] == spans_mod._MARK:
                    raw.append({"kind": "mark", "t": e[1],
                                "request_id": e[2], "phase": e[3],
                                "note": e[4]})
                else:
                    raw.append({"kind": "section", "t": e[1],
                                "name": e[2], "t_end": e[3]})
            files["spans_raw.jsonl"] = raw
        plan = engine.fault_plan
        if plan is not None:
            files["fault_plan.json"] = {
                "specs": [dataclasses.asdict(s) for s in plan.specs],
                "injected": [dataclasses.asdict(s)
                             for s in plan.injected],
                "counts": plan.counts(),
            }
        return files

    # -- deadlines + overload protection ------------------------------------

    def _expire(self, now: float) -> None:
        kept: Deque[Request] = collections.deque()
        n_free, n_slots = len(self._free), self.engine.slots
        pos = 0
        for r in self.queue:
            if self._expire_queued(r, now):
                continue
            # deadline-aware shedding: when the queue ahead already
            # implies missing this deadline, shed NOW — the client
            # learns immediately instead of after the deadline the
            # scheduler knew it would blow. The estimate accounts for
            # slot concurrency: a request that fits the free slots
            # admits THIS tick (never shed), the rest wait roughly one
            # measured chunk latency per wave of `slots` ahead of them
            wave = (pos - n_free) // n_slots + 1
            if (self.resilience.shed_deadlines and r.deadline is not None
                    and self._chunk_ewma > 0.0 and pos >= n_free
                    and now + wave * self._chunk_ewma > r.deadline):
                self._shed += 1
                self.tenants.stats(r.tenant).shed += 1
                if self.recorder is not None:
                    self.recorder.record("shed", r.request_id,
                                         "deadline")
                if self.telemetry is not None:
                    self.telemetry.shed["deadline"].inc()
                    self.telemetry.tenant(r.tenant)["shed"][
                        "deadline"].inc()
                self._abort(r, FINISH_TIMEOUT, now)
                continue
            kept.append(r)
            pos += 1
        self.queue = kept
        for slot in list(self.active):
            act = self.active.get(slot)
            if act is None:
                continue  # a retire-seam recovery below cleared it
            dl = act.request.deadline
            if dl is not None and now >= dl:
                # a timeout streams the matcher-held tail (nothing
                # matched — there is nothing to trim)
                self._flush_held(act)
                try:
                    self.engine.retire(slot)
                except Exception as e:  # device error escaping retire
                    # the expiring request still times out (its tokens
                    # so far are on the host); everyone else replays
                    self.events.append(StreamEvent(
                        act.request.request_id, None, True,
                        FINISH_TIMEOUT))
                    self._release(slot, FINISH_TIMEOUT)
                    self._recover(now, cause="retire", detail=str(e),
                                  affected=[])
                    continue
                self.events.append(StreamEvent(
                    act.request.request_id, None, True, FINISH_TIMEOUT))
                self._release(slot, FINISH_TIMEOUT)
        for rid in list(self._parked):
            pk = self._parked[rid]
            dl = pk.act.request.deadline
            if dl is not None and now >= dl:
                # a parked conversation's deadline still bites: drop
                # the swap payload and time out with the stream so far
                del self._parked[rid]
                try:
                    self._resume_q.remove(rid)
                except ValueError:
                    pass
                self.engine.drop_parked(rid)
                self._abort(pk.act.request, FINISH_TIMEOUT, now,
                            act=pk.act)

    def _expire_queued(self, request: Request, now: float) -> bool:
        dl = request.deadline
        if dl is None or now < dl:
            return False
        if self.recorder is not None:
            self.recorder.record("queue_expired", request.request_id)
        if self.telemetry is not None:
            self.telemetry.queue_expired.inc()
        self._abort(request, FINISH_TIMEOUT, now)
        return True

    # -- admission ----------------------------------------------------------

    def _admission_of(self, r: Request, slot: int) -> Admission:
        """Build one :class:`Admission` row from a request (shared by
        the batched, prefix-hit, and chunked admission paths so they
        can never disagree on the sampling surface)."""
        hit = self._prefix_hits.get(r.request_id)
        return Admission(
            slot=slot, prompt=r.prompt,
            max_tokens=r.max_tokens,
            temperature=r.sampling.temperature,
            top_k=r.sampling.top_k,
            top_p=r.sampling.top_p,
            seed=r.sampling.seed,
            eos_token_id=r.eos_token_id,
            allowed_tokens=(
                tuple(r.constraint.allowed_tokens())
                if r.constraint is not None else None),
            prefix_page=None if hit is None else hit[0],
            prefix_len=0 if hit is None else hit[1],
            adapter=r.adapter)

    def _request_pages_needed(self, r: Request) -> int:
        """One request's PRIVATE page need — copy-on-write prefix
        pages discounted (they pin, they don't allocate). The one
        spelling submit's never-fits guard, the admission page gate,
        and the backpressure telemetry all share."""
        hit = self._prefix_hits.get(r.request_id)
        return self.engine.pages_needed(
            len(r.prompt), r.max_tokens, 0 if hit is None else hit[1])

    def _note_pages_exhausted(self, r: Request, needed: int) -> None:
        """Backpressure, not a fault: the head request waits queued
        until releases free enough pages (an ingress layer sees the
        pressure as queue growth → :class:`QueueFull` 429s). Under
        oversubscription (:attr:`preempt`) the wait also triggers the
        WFQ preemption pass — the freed pages let the head admit next
        tick instead of waiting out a long-running lowest-priority
        stream."""
        self._pages_exhausted_waits += 1
        if self.recorder is not None:
            self.recorder.record(
                "pages_exhausted", r.request_id, needed,
                self.engine.page_allocator.free_pages)
        if self.telemetry is not None:
            self.telemetry.pages_exhausted.inc()
        self._maybe_preempt(r, needed)

    def _advance_chunked(self, now: float) -> None:
        """Drive the in-progress chunked-prefill admission one device
        dispatch forward (one ``prefill_extend`` chunk, or the
        finish). Decode dispatch follows in the same tick, so chunks
        and decode waves strictly alternate."""
        if self._chunked is None:
            return
        if self._chunked_fresh:
            # chunk 0 was dispatched by _start_chunked THIS tick —
            # one chunk forward per tick, strictly
            self._chunked_fresh = False
            return
        ca, r = self._chunked
        rec = self.recorder
        try:
            res = self.engine.admit_chunked_step(ca)
        except Exception as e:
            self._chunked = None
            self._recover(self.clock(), cause="admit", detail=str(e),
                          affected=[r], batch_reqs=[r])
            return
        if res is None:
            self._chunked_chunks += 1
            if rec is not None:
                rec.record("prefill_chunk", r.request_id,
                           ca.next_chunk - 1, ca.chunks_total)
            if self.telemetry is not None:
                self.telemetry.chunked_chunks.inc()
            return
        # the finish landed: the request occupies its slot from here on
        # — exactly the bookkeeping one _admit_queued row gets
        self._chunked = None
        t_first = self.clock()
        vocab = self.engine.cfg.vocab_size
        if not 0 <= res.first_token < vocab:
            self._recover(t_first, cause="invalid_token",
                          detail="invalid first token from chunked "
                          "admission (NaN-poisoned prefill)",
                          affected=[r], batch_reqs=[r])
            return
        slot = ca.slot
        self._chunked_admissions += 1
        self._admitted_requests += 1
        self._admit_dispatches += 1
        st = self._replay.get(r.request_id)
        act = _Active(r)
        act.suppress = 0 if st is None else len(st.tokens)
        act.first_token_time = t_first
        self.active[slot] = act
        if rec is not None:
            rec.record("admit", r.request_id, slot, res.bucket,
                       res.batch_size, res.group, 0)
        self.tenants.stats(r.tenant).admitted += 1
        tele = self.telemetry
        if tele is not None:
            tele.tenant(r.tenant)["admitted"].inc()
            tele.admitted.inc()
            tele.chunked_admissions.inc()
            tele.admit_dispatches.inc()
            if res.bucket in tele.bucket:
                tele.bucket[res.bucket].inc()
        if act.suppress < 1:
            self.ttft_stats.add(t_first - r.arrival_time)
            if self.slo is not None:
                self.slo.observe("ttft", t_first - r.arrival_time,
                                 r.tenant, now=t_first)
            if self._tuner is not None:
                self._tuner.observe_ttft(t_first - r.arrival_time)
            if self.spans is not None:
                self.spans.mark(r.request_id,
                                spans_mod.PHASE_FIRST_TOKEN)
            if tele is not None:
                tele.ttft.observe(t_first - r.arrival_time)
        reason = None
        if res.finished:
            reason = FINISH_EOS if res.hit_eos else FINISH_LENGTH
        self._ingest(slot, act, res.first_token, res.logprob, t_first,
                     device_done=res.finished, device_reason=reason)

    def _start_chunked(self, now: float) -> None:
        """Begin a chunked admission for the queue head when it
        qualifies: chunked prefill enabled, prompt longer than one
        chunk, no prefix-pool hit (a hit already skips the long
        forward), none already in progress, and a free slot + pages."""
        if (self._chunked is not None
                or not self.engine.chunked_prefill_enabled
                or not self._free or not self.queue):
            return
        r = self.queue[0]
        if not self.engine.chunked_for(len(r.prompt)) \
                or r.request_id in self._prefix_hits:
            return
        st = self._replay.get(r.request_id)
        if st is not None and now < st.not_before:
            return
        needed = self.engine.pages_needed(len(r.prompt), r.max_tokens)
        if not self.engine.can_admit_pages(len(r.prompt), r.max_tokens):
            self._note_pages_exhausted(r, needed)
            return
        self.queue.popleft()
        slot = self._free.pop()
        if r.constraint is not None:
            r.constraint.reset()
        if self.spans is not None:
            self.spans.mark(r.request_id, spans_mod.PHASE_PREFILL,
                            note=f"slot {slot} (chunked)")
        try:
            ca = self.engine.admit_chunked_start(
                self._admission_of(r, slot))
        except PagesExhausted as e:
            # a stale mapping race — requeue, the slot returns free
            self._free.append(slot)
            self.queue.appendleft(r)
            self._note_pages_exhausted(r, e.requested)
            return
        except Exception as e:
            self._free.append(slot)
            self._recover(self.clock(), cause="admit", detail=str(e),
                          affected=[r], batch_reqs=[r])
            return
        self._chunked = (ca, r)
        self._chunked_fresh = True
        self._chunked_chunks += 1
        if self.slo is not None and st is None:
            # the chunked path's queue wait lands when the request
            # leaves the queue (admission dispatch starts here)
            self.slo.observe("queue_wait", now - r.arrival_time,
                             r.tenant, now=now)
        if self.recorder is not None:
            self.recorder.record("prefill_chunk", r.request_id, 0,
                                 ca.chunks_total)
        if self.telemetry is not None:
            self.telemetry.chunked_chunks.inc()
            self.telemetry.queue_depth.set(len(self.queue))

    def _admit_eligible(self, r: Request, now: float) -> bool:
        """Whether a queued request may admit through the batched path
        THIS wave: its retry-backoff gate (if any) has opened, and it
        is not chunked-path-only (chunked-eligible prompts admit
        through the chunked path — one at a time, `_start_chunked`;
        batching one here would be exactly the monolithic
        long-prefill stall chunking exists to remove)."""
        st = self._replay.get(r.request_id)
        if st is not None and now < st.not_before:
            return False
        return not (self.engine.chunked_for(len(r.prompt))
                    and r.request_id not in self._prefix_hits)

    def _pop_eligible(self, now: float, n: int) -> List[Request]:
        """Pop up to ``n`` admissible queued requests, preserving
        queue order for the rest — a backing-off request must not
        block the head of the line.

        Pop ORDER is tenant-aware weighted-fair queueing
        (:mod:`apex_tpu.serving.tenancy`): each pick takes the
        head-of-line request of the backlogged tenant most behind its
        fair share (lowest served-tokens/weight deficit counter, aged
        by head-of-line wait so no tenant starves). Within a tenant
        order stays FIFO; with a single backlogged tenant every pick
        IS the first eligible request — the historical strict-FIFO
        scheduler, bit-identically."""
        book = self.tenants
        # ONE eligibility scan per wave (the historical single pass),
        # then n picks off the per-tenant head cursors — deficits do
        # not move between picks (tokens charge at emission), so
        # rescanning per pick would buy nothing but O(queue × n)
        by_tenant: Dict[str, List[Tuple[int, Request]]] = {}
        for idx, r in enumerate(self.queue):
            if self._admit_eligible(r, now):
                by_tenant.setdefault(r.tenant, []).append((idx, r))
        heads = {t: 0 for t in by_tenant}
        picked: List[Request] = []
        picked_idx: List[int] = []
        while len(picked) < n:
            live = {t: lst[heads[t]] for t, lst in by_tenant.items()
                    if heads[t] < len(lst)}
            if not live:
                break
            if len(live) == 1:
                t = next(iter(live))
            else:
                t = book.pick({
                    tt: max(now - (rr.arrival_time
                                   if rr.arrival_time is not None
                                   else now), 0.0)
                    for tt, (_, rr) in live.items()})
            idx, r = live[t]
            heads[t] += 1
            picked_idx.append(idx)
            picked.append(r)
        if picked_idx:
            drop = set(picked_idx)
            self.queue = collections.deque(
                r for i, r in enumerate(self.queue) if i not in drop)
        return picked

    def _admit_queued(self, now: float) -> None:
        # parked resumes first (their clients are waiting MID-stream),
        # then batched short admissions, chunked start last: the wave
        # of shorts must not queue behind chunk 0's forward (see
        # step()'s ordering note)
        if self._resume_q:
            self._admit_parked(now)
        self._admit_batches(now)
        self._start_chunked(now)

    def _chunked_head_pending(self) -> bool:
        """A chunked-eligible request heads the queue with none in
        progress — `_admit_batches` keeps one slot free for it (shorts
        admit first within a tick, but must not STARVE the long under
        sustained short traffic)."""
        if self._chunked is not None or not self.queue \
                or not self.engine.chunked_prefill_enabled:
            return False
        head = self.queue[0]
        return (self.engine.chunked_for(len(head.prompt))
                and head.request_id not in self._prefix_hits)

    def _admit_batches(self, now: float) -> None:
        while self.queue:
            reserve = 1 if self._chunked_head_pending() else 0
            if len(self._free) <= reserve:
                return
            n = min(len(self._free) - reserve, len(self.queue))
            if self.max_admit_batch is not None:
                n = min(n, self.max_admit_batch)
            reqs = self._pop_eligible(now, n)
            if not reqs:
                return  # queue gated on backoff / the chunked path
            if self.engine.paged:
                # allocator backpressure, FIFO-strict: admit the
                # prefix of the wave the free pages cover; the first
                # request that does not fit (and everything behind it)
                # stays queued until releases free pages
                free_p = self.engine.page_allocator.free_pages
                needed, cut, cut_need = 0, len(reqs), 0
                for idx, r in enumerate(reqs):
                    need = self._request_pages_needed(r)
                    if needed + need > free_p:
                        cut, cut_need = idx, need
                        break
                    needed += need
                if cut < len(reqs):
                    self.queue.extendleft(reversed(reqs[cut:]))
                    if cut == 0:
                        self._note_pages_exhausted(reqs[0], cut_need)
                        return
                    reqs = reqs[:cut]
            slots = [self._free.pop() for _ in range(len(reqs))]
            if self.spans is not None:
                for r, slot in zip(reqs, slots):
                    self.spans.mark(r.request_id, spans_mod.PHASE_PREFILL,
                                    note=f"slot {slot}")
            for r in reqs:
                # (re-)admission restarts the schema DFA from its
                # initial state — fault replay re-derives the stream
                # from the prompt, and the constraint must follow it
                if r.constraint is not None:
                    r.constraint.reset()
            t_admit = self.clock()

            try:
                results = self.engine.admit_many([
                    self._admission_of(r, slot)
                    for r, slot in zip(reqs, slots)])
            except PagesExhausted:
                # backpressure raced the pre-flight check (a stale
                # mapping, a share) — requeue and wait, no fault; the
                # event records the HEAD's own need (the exception's
                # `requested` is the whole batch's total)
                self._free.extend(reversed(slots))
                self.queue.extendleft(reversed(reqs))
                self._note_pages_exhausted(
                    reqs[0], self._request_pages_needed(reqs[0]))
                return
            except Exception as e:  # device error escaping the admit
                self._recover(self.clock(), cause="admit", detail=str(e),
                              affected=list(reqs), batch_reqs=list(reqs))
                return
            t_first = self.clock()
            # NaN-poisoned prefill: a garbage first token means the
            # admission's cache insert cannot be trusted — quarantine
            # before any event leaks, charging only the bad rows
            vocab = self.engine.cfg.vocab_size
            bad = [r for r, res in zip(reqs, results)
                   if not 0 <= res.first_token < vocab]
            if bad:
                self._recover(t_first, cause="invalid_token",
                              detail="invalid first token from admission "
                              "(NaN-poisoned prefill)",
                              affected=bad, batch_reqs=list(reqs))
                return
            n_groups = results[-1].group + 1
            self._admitted_requests += len(reqs)
            self._admit_dispatches += n_groups
            if self.spans is not None:
                self.spans.section_at("engine.admit", t_admit, t_first)
            tele = self.telemetry
            if tele is not None:
                tele.admit_dispatches.inc(n_groups)
                tele.queue_depth.set(len(self.queue))
            rows = list(zip(reqs, slots, results))
            rec = self.recorder
            for idx, (r, slot, res) in enumerate(rows):
                st = self._replay.get(r.request_id)
                act = _Active(r)
                act.suppress = 0 if st is None else len(st.tokens)
                act.first_token_time = t_first
                self.active[slot] = act
                self.tenants.stats(r.tenant).admitted += 1
                hit = self._prefix_hits.get(r.request_id)
                if rec is not None:
                    rec.record("admit", r.request_id, slot, res.bucket,
                               res.batch_size, res.group,
                               0 if hit is None else hit[1])
                if hit is not None and self.engine.paged:
                    # the hit mapped the prefix's pages copy-on-write
                    # — zero prefix bytes moved at admission
                    self._page_share_hits += 1
                    if rec is not None:
                        rec.record(
                            "page_share", r.request_id,
                            hit[1] // self.engine.engine_cfg.page_size)
                    if tele is not None:
                        tele.page_share_hits.inc()
                if tele is not None:
                    tele.admitted.inc()
                    tele.tenant(r.tenant)["admitted"].inc()
                    tele.admit_batch[res.batch_size].inc()
                    tele.bucket[res.bucket].inc()
                if act.suppress < 1:
                    # TTFT is "first token computed", recorded even
                    # when the stop matcher holds that token back from
                    # the wire; a replaying request's re-derived first
                    # token is not a first token
                    self.ttft_stats.add(t_first - r.arrival_time)
                    if self.slo is not None:
                        # queue wait is arrival → admission dispatch
                        # (the slice a router's predicted-TTFT models);
                        # TTFT adds the prefill on top
                        self.slo.observe(
                            "ttft", t_first - r.arrival_time,
                            r.tenant, now=t_first)
                        self.slo.observe(
                            "queue_wait", t_admit - r.arrival_time,
                            r.tenant, now=t_first)
                    if self._tuner is not None:
                        self._tuner.observe_ttft(
                            t_first - r.arrival_time)
                    if self.spans is not None:
                        self.spans.mark(r.request_id,
                                        spans_mod.PHASE_FIRST_TOKEN)
                    if tele is not None:
                        tele.ttft.observe(t_first - r.arrival_time)
                reason = None
                if res.finished:
                    reason = FINISH_EOS if res.hit_eos else FINISH_LENGTH
                if self._ingest(slot, act, res.first_token, res.logprob,
                                t_first, device_done=res.finished,
                                device_reason=reason) == _RECOVERED:
                    # a retire-seam fault rebuilt the engine mid-batch:
                    # rows not yet processed lost their slots — back to
                    # the queue's front (their events never emitted, so
                    # re-admission is a clean restart)
                    rest = [rr for rr, _, _ in rows[idx + 1:]]
                    self.queue.extendleft(reversed(rest))
                    if tele is not None:
                        tele.queue_depth.set(len(self.queue))
                    return

    def _release(self, slot: int, reason: str) -> None:
        act = self.active.pop(slot)
        self._free.append(slot)
        # paged: the slot's private pages return to the pool and its
        # table row redirects to the sink — this release is what frees
        # capacity for the backpressured queue head
        self.engine.free_slot(slot)
        now = self.clock()
        ttft = (None if act.first_token_time is None
                else act.first_token_time - act.request.arrival_time)
        st = self._replay.pop(act.request.request_id, None)
        tokens, lps = act.tokens, act.logprobs
        if st is not None and len(st.tokens) > len(tokens):
            # retired mid-replay: the pre-fault stream is longer than
            # what the replay re-derived — the completion must carry
            # everything the client was streamed
            tokens, lps = st.tokens, st.logprobs
        self._complete(act.request, tokens, reason, ttft=ttft, now=now,
                       logprobs=lps)

    def _complete(self, request: Request, tokens: List[int], reason: str,
                  *, ttft: Optional[float], now: float,
                  logprobs: Optional[List[float]] = None) -> None:
        self._prefix_hits.pop(request.request_id, None)
        arrival = request.arrival_time if request.arrival_time is not None \
            else now
        comp = Completion(request.request_id, list(tokens), reason,
                          ttft=ttft, latency=now - arrival,
                          logprobs=list(logprobs or []))
        self.completions[request.request_id] = comp
        if self.recorder is not None:
            self.recorder.record("finish", request.request_id, reason,
                                 len(tokens))
        self._journal_finish(request, tokens, logprobs, reason)
        rrec = self._req_records.pop(request.request_id, None)
        if rrec is not None:
            # the replayable record graduates to the bounded
            # completed-request ring with its final client stream
            rrec["status"] = "completed"
            rrec["finish_reason"] = reason
            rrec["emitted"] = list(tokens)
            self._req_done.append(rrec)
        if reason == FINISH_EOS and not tokens:
            # eos-terminal prompt: completes at submit, emits only the
            # finished event (no token)
            self.events.append(StreamEvent(
                request.request_id, None, True, reason))
        if self.slo is not None:
            self.slo.observe("e2e", comp.latency, request.tenant,
                             now=now)
        if self.telemetry is not None:
            self.telemetry.finished[reason].inc()
            self.telemetry.request_latency.observe(comp.latency)
        if self.spans is not None:
            self.spans.mark(request.request_id, spans_mod.PHASE_RETIRED,
                            note=reason)
        if self.metrics is not None:
            # no value for "no first token" — a -1.0 ttft sentinel
            # silently poisons any downstream mean/percentile, so the
            # key is simply absent for zero-token completions
            rec = {
                "completed": 1.0,
                "n_tokens": float(len(tokens)),
                "latency_s": comp.latency,
            }
            if ttft is not None:
                rec["ttft_s"] = ttft
            self.metrics.log(self._steps, rec)

    # -- reporting ---------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        """Aggregate serving metrics: throughput + latency percentiles
        (the bench's one JSON line)."""
        elapsed = None
        if self._started is not None:
            elapsed = max(self.clock() - self._started, 1e-9)
        out = {
            "requests_completed": float(len(self.completions)),
            "tokens_emitted": float(self._tokens_emitted),
            "steps": float(self._steps),
            "admitted_requests": float(self._admitted_requests),
            # batched admission's amortisation, directly: requests
            # prefilled per compiled admission dispatch
            "admit_dispatches": float(self._admit_dispatches),
            "pipeline_depth": float(self.pipeline_depth),
            # resilience: recoveries + overload actions this run
            "retries": float(self._retries),
            "retry_exhausted": float(self._retry_exhausted),
            "rebuilds": float(self._rebuilds),
            "shed": float(self._shed),
            "watchdog_trips": float(self._watchdog_trips),
            # fleet: requests handed to the on_evict hook (0 without a
            # router)
            "evicted_requests": float(self._evicted_requests),
            "health_state": float(self.health.code),
            # black box: post-mortem bundles written (auto + manual)
            "bundles_written": float(len(self.bundles_written)),
            # KV-cache capacity: slot-cache device bytes (quantized
            # data + scales) and the prefix pool's admission savings
            "cache_bytes": float(self.engine.cache_bytes()),
            "prefix_hits": float(self._prefix_hit_count),
            "prefix_misses": float(self._prefix_miss_count),
            # multi-tenant serving: live tenant population + rate-limit
            # rejections (per-tenant detail via tenant_summary())
            "tenants_seen": float(len(self.tenants.tenants_seen)),
            "tenant_throttled": float(self._throttled),
        }
        if self.engine.adapter_pool_enabled:
            out["adapters_registered"] = float(
                self.engine.adapters_registered)
        if self.engine.paged:
            # paged-cache capacity: occupancy, CoW sharing, chunked
            # admissions, and backpressure waits this run
            ps = self.engine.page_stats()
            out["pages_total"] = ps["pages_total"]
            out["pages_in_use"] = ps["pages_in_use"]
            out["pages_shared"] = ps["pages_shared"]
            out["page_fragmentation"] = ps["fragmentation"]
            out["page_share_hits"] = float(self._page_share_hits)
            out["pages_exhausted_waits"] = float(
                self._pages_exhausted_waits)
            out["pages_swapped"] = ps["pages_swapped"]
            out["swap_bytes"] = ps["swap_bytes"]
        if self.engine.host_swap_enabled:
            # the oversubscription ledger: conversations parked now,
            # swap traffic, and how the scheduler resolved pressure
            out["parked_conversations"] = float(len(self._parked))
            out["pauses"] = float(self._pauses)
            out["preemptions"] = float(self._preemptions)
            out["swap_resumes"] = float(self._swap_resumes)
            out["recompute_resumes"] = float(self._recompute_resumes)
            out["swap_capacity_drops"] = float(
                self._swap_capacity_drops)
            ap = self.engine.adapter_paging_stats()
            if ap is not None:
                for k, v in ap.items():
                    out[f"adapter_{k}"] = float(v)
        if self.engine.chunked_prefill_enabled:
            out["chunked_admissions"] = float(self._chunked_admissions)
            out["chunked_chunks"] = float(self._chunked_chunks)
        if self.journal is not None:
            # the durability ledger: appended/synced volume, rotation/
            # compaction churn, and requests this scheduler was
            # recovered with (0 for a fresh start)
            for k, v in self.journal.stats().items():
                out[f"journal_{k}"] = v
            out["journal_recovered_requests"] = float(
                self._journal_recovered)
        tn = self._tuner
        if self._gate is not None or (tn is not None
                                      and "spec_k" in tn.knobs):
            # speculative decoding: per-wave accounting (gate-driven
            # or tuner-driven) + gate state when a gate owns the knob
            out["spec_chunks"] = float(self._spec_chunks)
            out["spec_drafted"] = float(self._spec_drafted)
            out["spec_accepted"] = float(self._spec_accepted)
            out["spec_accept_rate"] = (
                self._spec_accepted / self._spec_drafted
                if self._spec_drafted else 0.0)
        if self._gate is not None:
            out["spec_gate_state"] = self._gate.state()
            out["spec_acceptance_ewma"] = self._gate.accept_ewma
            out["spec_break_even"] = self._gate.break_even()
        if tn is not None:
            # the control plane: state, decision counts, and the
            # incumbent operating point it steered to
            out["tuner_state"] = tn.state()
            out["tuner_probes"] = float(tn.probes_total)
            out["tuner_switches"] = float(
                sum(tn.switch_counts.values()))
            for k, v in tn.incumbent.items():
                out[f"tuner_{k}"] = float(v)
        if elapsed:
            out["tokens_per_sec"] = self._tokens_emitted / elapsed
        if self._decode_time > 0:
            # the steady-state half of the TTFT-vs-decode split: tokens
            # emitted by decode chunks per second of (overlap-dedup'd)
            # wall time spent on them (admission/prefill — the TTFT
            # side — excluded)
            out["decode_tokens_per_sec"] = (
                self._decode_tokens / self._decode_time)
            out["decode_tokens"] = float(self._decode_tokens)
            out["decode_time_s"] = self._decode_time
        for name, stats in (("ttft", self.ttft_stats),
                            ("token_latency", self.token_latency_stats)):
            for k, v in stats.summary().items():
                out[f"{name}_{k}"] = v
        if self.slo is not None:
            # the SLO observatory's sketch-backed percentiles (full-run
            # streaming, not the LatencyStats window) + alert roll-up
            out.update(self.slo.summary())
            out["predicted_ttft_s"] = self.predicted_ttft_s()
        return out
