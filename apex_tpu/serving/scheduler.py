"""Continuous-batching scheduler — the host loop around the engine.

Policy lives here, device mechanics in :mod:`apex_tpu.serving.engine`:
a FIFO request queue with backpressure (``max_queue``), per-request
deadlines (queued requests expire in place; active slots are retired),
admission of queued requests into free slots, a response stream
(:class:`apex_tpu.serving.request.StreamEvent`), and serving metrics —
TTFT, per-token latency, queue depth, slot occupancy, tokens/s —
aggregated via :class:`apex_tpu.profiler.LatencyStats` and emitted
through a :class:`apex_tpu.profiler.MetricsLogger` when one is given.

Observability (``apex_tpu.telemetry``): pass ``registry`` to count
admissions / finishes-by-reason / tokens and observe TTFT + per-token
latency into SLO-bucketed histograms (scrapeable live via
``telemetry.http.MetricsServer``), and ``spans`` to record each
request's phase timeline (queued → prefill → first_token → decode
chunks → retired) plus engine-dispatch sections, exportable as
Chrome-trace JSON. Both are pre-bound at construction so the per-token
hot path pays an attribute access and an add, nothing more.

The boundary fix the engine relies on: a request whose prompt already
ends in its eos token completes at ``submit`` time with zero generated
tokens — it never occupies a slot (admitting it would burn
``max_tokens`` steps decoding past a finished sequence).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Deque, Dict, List, Optional

from apex_tpu import profiler
from apex_tpu.serving.engine import Engine
from apex_tpu.serving.request import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_REASONS,
    FINISH_TIMEOUT,
    Completion,
    Request,
    StreamEvent,
)
from apex_tpu.telemetry import spans as spans_mod


class QueueFull(RuntimeError):
    """Backpressure signal: the request queue is at ``max_queue``."""


class _RegistryMetrics:
    """Pre-bound registry handles — children resolved once here so the
    scheduler's per-token path never does a name/label lookup."""

    def __init__(self, registry, slots: int):
        self.queue_depth = registry.gauge(
            "serving_queue_depth", "requests waiting for a slot")
        self.active_slots = registry.gauge(
            "serving_active_slots", "decode slots currently occupied")
        registry.gauge(
            "serving_slots_total", "decode slots in the engine"
        ).set(slots)
        self.submitted = registry.counter(
            "serving_requests_submitted_total", "requests accepted into "
            "the queue (or completed at submit)")
        self.admitted = registry.counter(
            "serving_requests_admitted_total",
            "requests prefilled into a slot")
        fin = registry.counter(
            "serving_requests_finished_total",
            "completed requests by finish reason", labels=("reason",))
        # pre-create every reason so a scrape shows explicit zeros
        self.finished = {r: fin.labels(reason=r) for r in FINISH_REASONS}
        self.queue_expired = registry.counter(
            "serving_queue_expired_total",
            "requests that blew their deadline while still queued")
        self.tokens = registry.counter(
            "serving_tokens_emitted_total", "generated tokens streamed")
        self.steps = registry.counter(
            "serving_scheduler_steps_total", "scheduler ticks")
        self.ttft = registry.histogram(
            "serving_ttft_seconds", "arrival to first token")
        self.token_latency = registry.histogram(
            "serving_token_latency_seconds",
            "per-token steady-decode latency (chunk wall time / chunk "
            "tokens)")
        self.request_latency = registry.histogram(
            "serving_request_latency_seconds", "arrival to completion")


class _Active:
    """Host view of one occupied slot."""

    __slots__ = ("request", "tokens", "first_token_time")

    def __init__(self, request: Request):
        self.request = request
        self.tokens: List[int] = []
        self.first_token_time: Optional[float] = None


class Scheduler:
    """Drive an :class:`Engine` over a stream of requests.

    >>> sched = Scheduler(engine)
    >>> sched.submit(Request("r0", prompt, max_tokens=16))
    >>> sched.run_until_idle()
    >>> sched.completions["r0"].tokens

    ``clock`` is injectable (tests drive deadlines with a fake clock);
    it must be monotonic. ``metrics`` receives one record per step plus
    one per completion.
    """

    def __init__(self, engine: Engine, *, max_queue: int = 256,
                 metrics: Optional[profiler.MetricsLogger] = None,
                 registry=None, spans=None,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.max_queue = max_queue
        self.metrics = metrics
        self.clock = clock
        #: telemetry sinks (both optional): a telemetry.Registry the
        #: scheduler counts/observes into, and a telemetry.SpanRecorder
        #: receiving per-request phase marks + dispatch sections. The
        #: recorder's clock is slaved to the scheduler's so injected
        #: test clocks produce deterministic timelines.
        self.telemetry = (None if registry is None
                          else _RegistryMetrics(registry, engine.slots))
        self.spans = spans
        if spans is not None:
            spans.clock = self.clock
        self.queue: Deque[Request] = collections.deque()
        self.active: Dict[int, _Active] = {}
        self.completions: Dict[str, Completion] = {}
        self.events: Deque[StreamEvent] = collections.deque()
        self.ttft_stats = profiler.LatencyStats()
        self.token_latency_stats = profiler.LatencyStats()
        self._free: List[int] = list(range(engine.slots))[::-1]
        self._steps = 0
        self._tokens_emitted = 0
        self._started: Optional[float] = None
        self._last_step_time: Optional[float] = None
        # steady-decode split: wall time inside engine.step() and the
        # tokens it emitted — TTFT (admission/prefill) excluded, so
        # summary() can report the two regimes separately
        self._decode_time = 0.0
        self._decode_tokens = 0

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request) -> None:
        """Enqueue ``request``; raises :class:`QueueFull` at capacity.
        Prompt-validity errors raise immediately; a prompt that already
        ends in the request's eos token completes here with zero
        generated tokens."""
        if request.request_id in self.completions or any(
                a.request.request_id == request.request_id
                for a in self.active.values()) or any(
                r.request_id == request.request_id for r in self.queue):
            raise ValueError(f"duplicate request_id {request.request_id!r}")
        request.sampling.validate()
        prompt = list(request.prompt)
        ecfg = self.engine.engine_cfg
        # the slot must fit prompt + at least one generated token
        limit = min(ecfg.max_prompt_len, ecfg.max_seq_len - 1)
        if not 1 <= len(prompt) <= limit:
            raise ValueError(
                f"prompt length {len(prompt)} outside [1, {limit}]")
        room = ecfg.max_seq_len - len(prompt)
        if not 1 <= request.max_tokens <= room:
            raise ValueError(
                f"max_tokens {request.max_tokens} outside [1, {room}] "
                f"for a {len(prompt)}-token prompt at max_seq_len "
                f"{ecfg.max_seq_len} — a clamped budget would silently "
                f"break solo-generate parity")
        eos = request.eos_token_id
        if eos is not None and not 0 <= eos < self.engine.cfg.vocab_size:
            raise ValueError(
                f"eos_token_id {eos} outside vocab "
                f"[0, {self.engine.cfg.vocab_size})")
        now = self.clock()
        request.arrival_time = now
        if (request.eos_token_id is not None
                and prompt[-1] == request.eos_token_id):
            if self.telemetry is not None:
                self.telemetry.submitted.inc()
            self._complete(request, [], FINISH_EOS, ttft=None, now=now)
            return
        if len(self.queue) >= self.max_queue:
            raise QueueFull(
                f"queue at capacity ({self.max_queue}); retry later")
        self.queue.append(request)
        if self.telemetry is not None:
            self.telemetry.submitted.inc()
            self.telemetry.queue_depth.set(len(self.queue))
        if self.spans is not None:
            self.spans.mark(request.request_id, spans_mod.PHASE_QUEUED)

    # -- the loop ----------------------------------------------------------

    def step(self) -> None:
        """One scheduler tick: expire deadlines, admit into free slots,
        advance the engine one decode CHUNK (``decode_chunk`` tokens
        per live slot, one dispatch) if any slot is live, and unpack
        the chunk's per-token stream events in emission order.
        Deadlines and admissions are checked between chunks — the
        ``decode_chunk`` admission-latency/throughput tradeoff."""
        now = self.clock()
        if self._started is None:
            self._started = now
        self._expire(now)
        self._admit_queued(now)
        if self.active:
            before = self.clock()
            tokens, finished = self.engine.step()
            dt = self.clock() - before
            if self.spans is not None:
                # one section per dispatch + a decode mark per slot
                # that rode the chunk (each O(1) ring appends)
                self.spans.section_at("engine.step", before, before + dt)
                for act in self.active.values():
                    self.spans.mark(act.request.request_id,
                                    spans_mod.PHASE_DECODE)
            n_cols = tokens.shape[1]
            per_tok = dt / n_cols
            self._decode_time += dt
            tele = self.telemetry
            for j in range(n_cols):
                # slots released at an earlier column drop out of
                # active; their remaining columns are pad by contract
                for slot in list(self.active):
                    act = self.active[slot]
                    tok = int(tokens[slot, j])
                    act.tokens.append(tok)
                    self._tokens_emitted += 1
                    self._decode_tokens += 1
                    self.token_latency_stats.add(per_tok)
                    if tele is not None:
                        tele.tokens.inc()
                        tele.token_latency.observe(per_tok)
                    done = bool(finished[slot, j])
                    reason = None
                    if done:
                        eos = act.request.eos_token_id
                        reason = (FINISH_EOS
                                  if eos is not None and tok == eos
                                  else FINISH_LENGTH)
                    self.events.append(StreamEvent(
                        act.request.request_id, tok, done, reason))
                    if done:
                        self._release(slot, reason)
        self._steps += 1
        if self.telemetry is not None:
            self.telemetry.steps.inc()
            self.telemetry.queue_depth.set(len(self.queue))
            self.telemetry.active_slots.set(len(self.active))
        if self.metrics is not None:
            elapsed = max(self.clock() - self._started, 1e-9)
            self.metrics.log(self._steps, {
                "queue_depth": len(self.queue),
                "slot_occupancy": len(self.active) / self.engine.slots,
                "tokens_emitted": self._tokens_emitted,
                "tokens_per_sec": self._tokens_emitted / elapsed,
            })

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        """Step until queue and slots are empty (offline batch mode)."""
        steps = 0
        while self.queue or self.active:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"not idle after {max_steps} steps — live slots "
                    f"{sorted(self.active)}, queue {len(self.queue)}")

    def pop_events(self) -> List[StreamEvent]:
        """Drain the response stream."""
        out = list(self.events)
        self.events.clear()
        return out

    # -- internals ---------------------------------------------------------

    def _expire(self, now: float) -> None:
        self.queue = collections.deque(
            r for r in self.queue
            if not self._expire_queued(r, now))
        for slot in list(self.active):
            act = self.active[slot]
            dl = act.request.deadline
            if dl is not None and now >= dl:
                self.engine.retire(slot)
                self.events.append(StreamEvent(
                    act.request.request_id, None, True, FINISH_TIMEOUT))
                self._release(slot, FINISH_TIMEOUT)

    def _expire_queued(self, request: Request, now: float) -> bool:
        dl = request.deadline
        if dl is None or now < dl:
            return False
        if self.telemetry is not None:
            self.telemetry.queue_expired.inc()
        self._complete(request, [], FINISH_TIMEOUT, ttft=None, now=now)
        self.events.append(StreamEvent(
            request.request_id, None, True, FINISH_TIMEOUT))
        return True

    def _admit_queued(self, now: float) -> None:
        while self._free and self.queue:
            request = self.queue.popleft()
            slot = self._free.pop()
            sp = request.sampling
            if self.spans is not None:
                self.spans.mark(request.request_id,
                                spans_mod.PHASE_PREFILL,
                                note=f"slot {slot}")
                t_admit = self.clock()
            first, hit_eos, done = self.engine.admit(
                slot, request.prompt, request.max_tokens,
                temperature=sp.temperature, top_k=sp.top_k, top_p=sp.top_p,
                seed=sp.seed,
                eos_token_id=request.eos_token_id)
            act = _Active(request)
            t_first = self.clock()
            act.first_token_time = t_first
            act.tokens.append(first)
            self._tokens_emitted += 1
            self.ttft_stats.add(t_first - request.arrival_time)
            if self.spans is not None:
                self.spans.section_at("engine.admit", t_admit, t_first)
                self.spans.mark(request.request_id,
                                spans_mod.PHASE_FIRST_TOKEN)
            if self.telemetry is not None:
                self.telemetry.admitted.inc()
                self.telemetry.tokens.inc()
                self.telemetry.queue_depth.set(len(self.queue))
                self.telemetry.ttft.observe(t_first - request.arrival_time)
            reason = None
            if done:
                reason = FINISH_EOS if hit_eos else FINISH_LENGTH
            self.events.append(StreamEvent(
                request.request_id, first, done, reason))
            self.active[slot] = act
            if done:
                self._release(slot, reason)

    def _release(self, slot: int, reason: str) -> None:
        act = self.active.pop(slot)
        self._free.append(slot)
        now = self.clock()
        ttft = (None if act.first_token_time is None
                else act.first_token_time - act.request.arrival_time)
        self._complete(act.request, act.tokens, reason, ttft=ttft, now=now)

    def _complete(self, request: Request, tokens: List[int], reason: str,
                  *, ttft: Optional[float], now: float) -> None:
        arrival = request.arrival_time if request.arrival_time is not None \
            else now
        comp = Completion(request.request_id, list(tokens), reason,
                          ttft=ttft, latency=now - arrival)
        self.completions[request.request_id] = comp
        if reason == FINISH_EOS and not tokens:
            # eos-terminal prompt: completes at submit, emits only the
            # finished event (no token)
            self.events.append(StreamEvent(
                request.request_id, None, True, reason))
        if self.telemetry is not None:
            self.telemetry.finished[reason].inc()
            self.telemetry.request_latency.observe(comp.latency)
        if self.spans is not None:
            self.spans.mark(request.request_id, spans_mod.PHASE_RETIRED,
                            note=reason)
        if self.metrics is not None:
            # no value for "no first token" — a -1.0 ttft sentinel
            # silently poisons any downstream mean/percentile, so the
            # key is simply absent for zero-token completions
            rec = {
                "completed": 1.0,
                "n_tokens": float(len(tokens)),
                "latency_s": comp.latency,
            }
            if ttft is not None:
                rec["ttft_s"] = ttft
            self.metrics.log(self._steps, rec)

    # -- reporting ---------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        """Aggregate serving metrics: throughput + latency percentiles
        (the bench's one JSON line)."""
        elapsed = None
        if self._started is not None:
            elapsed = max(self.clock() - self._started, 1e-9)
        out = {
            "requests_completed": float(len(self.completions)),
            "tokens_emitted": float(self._tokens_emitted),
            "steps": float(self._steps),
        }
        if elapsed:
            out["tokens_per_sec"] = self._tokens_emitted / elapsed
        if self._decode_time > 0:
            # the steady-state half of the TTFT-vs-decode split: tokens
            # emitted by engine.step() per second of wall time spent in
            # it (admission/prefill — the TTFT side — excluded)
            out["decode_tokens_per_sec"] = (
                self._decode_tokens / self._decode_time)
            out["decode_tokens"] = float(self._decode_tokens)
            out["decode_time_s"] = self._decode_time
        for name, stats in (("ttft", self.ttft_stats),
                            ("token_latency", self.token_latency_stats)):
            for k, v in stats.summary().items():
                out[f"{name}_{k}"] = v
        return out
