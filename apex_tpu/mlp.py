"""Fused MLP — apex/mlp/mlp.py (U) over csrc/mlp_cuda.cu (U).

Apex's ``MLP`` chains GEMM+bias+activation through one cuBLASLt-epilogue
CUDA call to dodge kernel-launch and memory-roundtrip overhead. Under XLA
the equivalent fusion is automatic: bias add and activation fuse into the
matmul's epilogue during compilation, and there are no launches to
amortise — so the TPU-native "fused MLP" is the straight-line jnp chain,
kept as an API-parity module (same constructor surface: layer sizes, bias
flag, activation choice). bf16 inputs hit the MXU with fp32 accumulation.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp


_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "gelu": lambda x: jax.nn.gelu(x, approximate=True),
}


def mlp(x, params, *, activation: str = "relu", final_activation: bool = False):
    """Apply the layer chain; ``params`` is a list of {kernel[, bias]}.

    Activation after every layer except (by default) the last — apex's MLP
    applies ReLU between layers only (U).
    """
    act = _ACTIVATIONS[activation]
    n = len(params)
    for i, p in enumerate(params):
        x = jnp.matmul(x, p["kernel"])
        if "bias" in p:
            x = x + p["bias"]
        if i < n - 1 or final_activation:
            x = act(x)
    return x


@dataclasses.dataclass(frozen=True)
class MLP:
    """apex.mlp.MLP (U): ``MLP(mlp_sizes, bias=True, activation='relu')``."""

    sizes: Sequence[int]  # [in, hidden..., out]
    bias: bool = True
    activation: str = "relu"
    param_dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if len(self.sizes) < 2:
            raise ValueError("MLP needs at least [in, out] sizes")
        if self.activation not in _ACTIVATIONS:
            raise ValueError(f"unknown activation {self.activation!r}")

    def init(self, key):
        params = []
        keys = jax.random.split(key, len(self.sizes) - 1)
        for k, fan_in, fan_out in zip(keys, self.sizes[:-1], self.sizes[1:]):
            # apex uses kaiming-uniform-style init from nn.Linear defaults
            bound = 1.0 / fan_in ** 0.5
            layer = {
                "kernel": jax.random.uniform(
                    k, (fan_in, fan_out), self.param_dtype, -bound, bound)
            }
            if self.bias:
                layer["bias"] = jnp.zeros((fan_out,), self.param_dtype)
            params.append(layer)
        return params

    def apply(self, params, x):
        return mlp(x, params, activation=self.activation)
