"""ResNet-50 (NHWC) with SyncBatchNorm — BASELINE configs #1/#3.

The reference exercises this model via examples/imagenet/main_amp.py (U)
(torchvision resnet50 + amp O1 + apex DDP) and the RetinaNet config
(SyncBatchNorm + FusedSGD). Functional NHWC implementation: params and
BatchNorm running-stats are separate pytrees (stats are *state*, not
weights — apex mutates buffers in place; here they are carried), and every
BN can reduce its batch moments over the dp axis via
:mod:`apex_tpu.parallel.sync_batchnorm`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.sync_batchnorm import sync_batch_norm

#: depth 26 = one bottleneck per stage — the smallest member of the
#: family, used by the CPU test backbone where ResNet-50 compiles slowly
_STAGES = {26: (1, 1, 1, 1), 50: (3, 4, 6, 3), 101: (3, 4, 23, 3),
           152: (3, 8, 36, 3)}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    #: mesh axis for cross-replica BN stats; None = local BN (apex DDP
    #: without convert_syncbn_model)
    bn_axis: Optional[str] = None
    compute_dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5

    @property
    def stages(self):
        if self.depth not in _STAGES:
            raise ValueError(f"unsupported depth {self.depth}")
        return _STAGES[self.depth]


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    std = (2.0 / fan_in) ** 0.5
    return std * jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)


def _bn_init(c):
    return ({"scale": jnp.ones((c,), jnp.float32),
             "bias": jnp.zeros((c,), jnp.float32)},
            {"mean": jnp.zeros((c,), jnp.float32),
             "var": jnp.ones((c,), jnp.float32)})


def _bottleneck_init(key, cin, planes, stride):
    cout = planes * 4
    ks = jax.random.split(key, 4)
    p, s = {}, {}
    p["conv1"] = _conv_init(ks[0], 1, 1, cin, planes)
    p["bn1"], s["bn1"] = _bn_init(planes)
    p["conv2"] = _conv_init(ks[1], 3, 3, planes, planes)
    p["bn2"], s["bn2"] = _bn_init(planes)
    p["conv3"] = _conv_init(ks[2], 1, 1, planes, cout)
    p["bn3"], s["bn3"] = _bn_init(cout)
    if stride != 1 or cin != cout:
        p["downsample"] = _conv_init(ks[3], 1, 1, cin, cout)
        p["bn_ds"], s["bn_ds"] = _bn_init(cout)
    return p, s


def init(cfg: ResNetConfig, key) -> Tuple[Any, Any]:
    """Returns (params, bn_state)."""
    keys = jax.random.split(key, 2 + sum(cfg.stages))
    p: Any = {"stem": _conv_init(keys[0], 7, 7, 3, cfg.width)}
    s: Any = {}
    p["bn_stem"], s["bn_stem"] = _bn_init(cfg.width)
    cin = cfg.width
    ki = 1
    for si, (n_blocks, planes) in enumerate(
            zip(cfg.stages, (64, 128, 256, 512))):
        blocks_p, blocks_s = [], []
        for b in range(n_blocks):
            stride = 2 if (b == 0 and si > 0) else 1
            bp, bs = _bottleneck_init(keys[ki], cin, planes, stride)
            ki += 1
            blocks_p.append(bp)
            blocks_s.append(bs)
            cin = planes * 4
        p[f"layer{si + 1}"] = blocks_p
        s[f"layer{si + 1}"] = blocks_s
    p["fc"] = {
        "kernel": 0.01 * jax.random.normal(
            keys[ki], (cin, cfg.num_classes), jnp.float32),
        "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return p, s


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(cfg: ResNetConfig, x, p, st, training):
    y, rm, rv = sync_batch_norm(
        x, p["scale"], p["bias"], st["mean"], st["var"],
        axis=cfg.bn_axis, momentum=cfg.bn_momentum, eps=cfg.bn_eps,
        training=training, channel_axis=-1)
    new_st = {"mean": rm, "var": rv} if training else st
    return y, new_st


def _bottleneck(cfg, x, p, st, stride, training):
    ns = {}
    y = _conv(x, p["conv1"])
    y, ns["bn1"] = _bn(cfg, y, p["bn1"], st["bn1"], training)
    y = jax.nn.relu(y)
    y = _conv(y, p["conv2"], stride)
    y, ns["bn2"] = _bn(cfg, y, p["bn2"], st["bn2"], training)
    y = jax.nn.relu(y)
    y = _conv(y, p["conv3"])
    y, ns["bn3"] = _bn(cfg, y, p["bn3"], st["bn3"], training)
    if "downsample" in p:
        sc = _conv(x, p["downsample"], stride)
        sc, ns["bn_ds"] = _bn(cfg, sc, p["bn_ds"], st["bn_ds"], training)
    else:
        sc = x
    return jax.nn.relu(y + sc), ns


def features(cfg: ResNetConfig, params, state, x, *, training: bool = True):
    """x [N, H, W, 3] → (stage feature maps {"c2".."c5"} NHWC in compute
    dtype, new_bn_state) — the multi-scale backbone surface detection
    heads consume (BASELINE config #3's RetinaNet pairing)."""
    x = x.astype(cfg.compute_dtype)
    ns: Any = {}
    feats: Any = {}
    y = _conv(x, params["stem"], 2)
    y, ns["bn_stem"] = _bn(cfg, y, params["bn_stem"], state["bn_stem"],
                           training)
    y = jax.nn.relu(y)
    y = lax.reduce_window(
        y, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])
    for si, n_blocks in enumerate(cfg.stages):
        layer_p = params[f"layer{si + 1}"]
        layer_s = state[f"layer{si + 1}"]
        new_blocks = []
        for b in range(n_blocks):
            stride = 2 if (b == 0 and si > 0) else 1
            y, bs = _bottleneck(cfg, y, layer_p[b], layer_s[b], stride,
                                training)
            new_blocks.append(bs)
        ns[f"layer{si + 1}"] = new_blocks
        feats[f"c{si + 2}"] = y
    return feats, ns


def forward(cfg: ResNetConfig, params, state, x, *, training: bool = True):
    """x [N, H, W, 3] → (logits [N, classes] fp32, new_bn_state)."""
    feats, ns = features(cfg, params, state, x, training=training)
    y = feats[f"c{len(cfg.stages) + 1}"]
    y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
    logits = y @ params["fc"]["kernel"] + params["fc"]["bias"]
    return logits, ns


def loss(cfg: ResNetConfig, params, state, images, labels, *,
         training: bool = True):
    """Mean softmax CE; returns (loss, new_bn_state)."""
    logits, ns = forward(cfg, params, state, images, training=training)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    return jnp.mean(nll), ns


def make_train_step(cfg: ResNetConfig, mesh, optimizer, scaler_cfg=None,
                    *, clip_grad_norm=None):
    """(init_fn, step_fn) for classification training — BASELINE config
    #1's trainer role, the ResNet analogue of
    :func:`apex_tpu.models.training.make_train_step`.

    ``step_fn(state, images, labels) -> (state, metrics)`` with the BN
    running stats riding ``TrainState.extra``. ``cfg.bn_axis="dp"``
    (SyncBatchNorm) syncs the batch statistics inside the forward, so
    the trainer skips its own dp-pmean of the stats; local BN instead
    gets the torch-DDP broadcast-buffers behaviour (stats dp-pmeaned
    each step). uint8 image batches (the native loader's wire format)
    are dequantized+normalized on device.
    """
    from apex_tpu import data as _data
    from apex_tpu.models import training as _training

    def loss_fn(p, bn_state, images, labels):
        if images.dtype == jnp.uint8:
            images = _data.normalize_images(images, jnp.float32)
        return loss(cfg, p, bn_state, images, labels)

    p_shapes, _ = jax.eval_shape(
        lambda: init(cfg, jax.random.PRNGKey(0)))
    # "already synced" only if the BN reduction axis covers dp — a
    # bn_axis of e.g. "tp" still leaves stats dp-divergent and needing
    # the trainer's pmean (torch DDP's broadcast-buffers role)
    bn_axes = (() if cfg.bn_axis is None
               else (cfg.bn_axis,) if isinstance(cfg.bn_axis, str)
               else tuple(cfg.bn_axis))
    return _training.make_loss_train_step(
        loss_fn, mesh, optimizer,
        init_params=lambda key: init(cfg, key),
        pspecs=jax.tree.map(lambda _: P(), p_shapes),
        scaler_cfg=scaler_cfg,
        clip_grad_norm=clip_grad_norm,
        init_extra="with_params",
        extra_sync_dp=("dp" not in bn_axes),
        n_batch_args=2,
    )
