"""BERT encoder + MLM head — BASELINE config #2 (BERT-large pretraining
with FusedLAMB + FusedLayerNorm under amp O2).

Reuses the tensor/sequence-parallel transformer stack from
:mod:`apex_tpu.models.gpt` with bidirectional attention (``causal=False``),
adding BERT's embedding pipeline (word + position + token-type, then
LayerNorm) and the tied masked-LM head. The loss is vocab-parallel CE
weighted by the MLM mask — the fmha/BERT path the reference optimises
(apex/contrib/fmha targets BERT seqlens (U)).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.kernels import layer_norm
from apex_tpu.models import gpt
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import init_method_normal
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528
    hidden_size: int = 1024   # BERT-large
    num_layers: int = 24
    num_heads: int = 16
    seq_len: int = 512
    type_vocab_size: int = 2
    sequence_parallel: bool = False
    remat: bool = True
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    layernorm_epsilon: float = 1e-12  # BERT convention
    init_std: float = 0.02
    axis: str = "tp"
    # perf knobs, forwarded to the core stack (same measured v5e guidance
    # as GPT — docs/DESIGN.md "Performance engineering")
    remat_policy: Any = None
    attn_impl: str = "auto"   # auto → flash at seq ≥256 on TPU
    attn_layout: str = "auto"  # auto → lane-packed flash; "bhsd" opts out
    ln_impl: str = "xla"      # measured winner in-model (docs/DESIGN.md)
    attn_score_dtype: str = "f32"
    scan_unroll: Any = 1
    #: ZeRO-3 param sharding of the encoder stack (see GPTConfig.fsdp);
    #: the BERT-specific leaves (token-type/mlm head) stay replicated
    fsdp: bool = False

    def core(self) -> gpt.GPTConfig:
        return gpt.GPTConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            num_layers=self.num_layers, num_heads=self.num_heads,
            seq_len=self.seq_len, sequence_parallel=self.sequence_parallel,
            remat=self.remat, compute_dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
            layernorm_epsilon=self.layernorm_epsilon,
            init_std=self.init_std, axis=self.axis, causal=False,
            remat_policy=self.remat_policy, attn_impl=self.attn_impl,
            attn_layout=self.attn_layout, ln_impl=self.ln_impl,
            attn_score_dtype=self.attn_score_dtype,
            scan_unroll=self.scan_unroll, fsdp=self.fsdp)


def init(cfg: BertConfig, key) -> Any:
    k_core, k_tt, k_head = jax.random.split(key, 3)
    core = gpt.init(cfg.core(), k_core)
    h = cfg.hidden_size
    dt = cfg.param_dtype
    emb_init = init_method_normal(cfg.init_std)
    core["embedding"]["token_type"] = emb_init(
        k_tt, (cfg.type_vocab_size, h), dt)
    core["embedding"]["ln"] = {"scale": jnp.ones((h,), dt),
                               "bias": jnp.zeros((h,), dt)}
    core["mlm_head"] = {
        "dense": {"kernel": emb_init(k_head, (h, h), dt),
                  "bias": jnp.zeros((h,), dt)},
        "ln": {"scale": jnp.ones((h,), dt), "bias": jnp.zeros((h,), dt)},
        # decoder is tied to the word embedding; per-vocab bias is sharded
        "bias": jnp.zeros((cfg.vocab_size,), dt),
    }
    return core


def param_specs(cfg: BertConfig) -> Any:
    specs = gpt.param_specs(cfg.core())
    specs["embedding"]["token_type"] = P(None, None)
    specs["embedding"]["ln"] = {"scale": P(None), "bias": P(None)}
    specs["mlm_head"] = {
        "dense": {"kernel": P(None, None), "bias": P(None)},
        "ln": {"scale": P(None), "bias": P(None)},
        "bias": P(cfg.axis),
    }
    return specs


def _embed(cfg: BertConfig, params, tokens, token_type_ids):
    core = cfg.core()
    h = gpt._embed(core, params, tokens)  # [b, s(_local), h] post-scatter
    # token-type + embedding LN ride on top; under SP they apply to the
    # seq-sharded activations (type embedding is position-independent)
    tt = jnp.take(params["embedding"]["token_type"], token_type_ids, axis=0)
    tt = tt.astype(cfg.compute_dtype)  # [b, s, h]
    if cfg.sequence_parallel:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            scatter_to_sequence_parallel_region,
        )
        tt = scatter_to_sequence_parallel_region(tt, cfg.axis, 1)
    h = h + tt
    return layer_norm(h, params["embedding"]["ln"]["scale"],
                      params["embedding"]["ln"]["bias"],
                      eps=cfg.layernorm_epsilon)


def hidden_states(cfg: BertConfig, params, tokens, token_type_ids=None):
    """[b, s] ids → [b, s(_local), h] final hidden (post final-LN)."""
    from jax import lax as _lax

    core = cfg.core()
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(tokens)
    h = _embed(cfg, params, tokens, token_type_ids)

    def body(carry, layer_p):
        # dense core (no MoE in the BERT stack): aux term is always 0
        return gpt._block(core, gpt._cast_layer(core, layer_p), carry)[0], None

    if cfg.remat:
        from apex_tpu.transformer.tensor_parallel import random as tpr
        body = tpr.checkpoint(body)
    h, _ = _lax.scan(body, h, params["layers"])
    return layer_norm(h, params["final_ln"]["scale"],
                      params["final_ln"]["bias"],
                      eps=cfg.layernorm_epsilon)


def mlm_logits(cfg: BertConfig, params, tokens, token_type_ids=None):
    """Vocab-sharded MLM logits [b, s, vocab/tp]."""
    h = hidden_states(cfg, params, tokens, token_type_ids)
    if cfg.sequence_parallel:
        h = gather_from_sequence_parallel_region(h, cfg.axis, True, 1)
    else:
        h = copy_to_tensor_model_parallel_region(h, cfg.axis)
    head = params["mlm_head"]
    h = jnp.matmul(h, head["dense"]["kernel"].astype(cfg.compute_dtype))
    h = h + head["dense"]["bias"].astype(cfg.compute_dtype)
    h = jax.nn.gelu(h, approximate=True)
    h = layer_norm(h, head["ln"]["scale"], head["ln"]["bias"],
                   eps=cfg.layernorm_epsilon)
    table = params["embedding"]["word"]["table"].astype(cfg.compute_dtype)
    lg = jnp.einsum("bsh,vh->bsv", h, table)
    return lg + head["bias"].astype(cfg.compute_dtype)


def mlm_loss(cfg: BertConfig, params, tokens, targets, mlm_mask,
             token_type_ids=None):
    """Masked-LM loss: mean CE over positions where ``mlm_mask`` is 1.

    ``tokens``/``targets``/``mlm_mask``: [b, s]; targets hold original ids
    at masked positions (ignored elsewhere).
    """
    lg = mlm_logits(cfg, params, tokens, token_type_ids).astype(jnp.float32)
    per_tok = vocab_parallel_cross_entropy(lg, targets, 0.0, cfg.axis)
    w = mlm_mask.astype(jnp.float32)
    return jnp.sum(per_tok * w) / jnp.maximum(jnp.sum(w), 1.0)


def seq_partial_grad_mask(cfg: BertConfig) -> Any:
    """BERT's sequence-parallel tp-psum mask: the core stack's mask plus
    the embedding LN (applied to seq-sharded activations, so its grads
    are tp-partial) — the mlm head runs after the SP gather and is
    already full."""
    mask = gpt.seq_partial_grad_mask(cfg.core())
    mask["embedding"]["token_type"] = False
    mask["embedding"]["ln"] = {"scale": True, "bias": True}
    mask["mlm_head"] = {
        "dense": {"kernel": False, "bias": False},
        "ln": {"scale": False, "bias": False},
        "bias": False,
    }
    return mask


def make_mlm_train_step(cfg: BertConfig, mesh, optimizer,
                        scaler_cfg=None, *, clip_grad_norm=None):
    """(init_fn, step_fn) for MLM pretraining — BASELINE config #2's
    trainer role, the BERT analogue of
    :func:`apex_tpu.models.training.make_train_step`.

    ``step_fn(state, tokens, targets, mlm_mask) -> (state, metrics)``;
    composes dp / tp / SP / fsdp, amp loss scaling, and the global-L2
    clip through :func:`training.make_loss_train_step`.
    """
    from apex_tpu.mesh.topology import mesh_shape_of
    from apex_tpu.models import training as _training

    if cfg.fsdp:
        # same build-time guards as the GPT builder (training.py): the
        # constraints are model-shaped, so the generic core can't check
        if not cfg.remat:
            raise ValueError(
                "fsdp requires remat=True: without recompute the "
                "all-gathered full kernels are saved as backward "
                "residuals, costing MORE memory than fsdp=False")
        dp = mesh_shape_of(mesh).get("dp", 1)
        if dp > 1 and cfg.hidden_size % dp:
            raise ValueError(
                f"fsdp shards the kernels' h-dim: hidden_size "
                f"{cfg.hidden_size} must divide by dp={dp}")

    def loss_fn(p, tokens, targets, mlm_mask):
        return mlm_loss(cfg, p, tokens, targets, mlm_mask)

    return _training.make_loss_train_step(
        loss_fn, mesh, optimizer,
        init_params=lambda key: init(cfg, key),
        pspecs=param_specs(cfg),
        scaler_cfg=scaler_cfg,
        clip_grad_norm=clip_grad_norm,
        sp_psum_mask=(seq_partial_grad_mask(cfg)
                      if cfg.sequence_parallel else None),
        model_axis=cfg.axis,
        fsdp=cfg.fsdp,
        n_batch_args=3,
    )
